"""Closed-loop SLA autoscale controller: predictor → planner → operator.

ROADMAP item 4: every ingredient existed — ``planner/load_predictor.py``
(seasonal/ARIMA), ``planner/planner_core.py`` (capacity inversion +
adaptive corrections), ``deploy/operator.py`` (reconcile), QoS classes
(PR 5), SIGTERM drain (PR 3) — but decisions stopped at a log line or a KV
key and nothing verified they MATERIALIZED. This controller closes the
loop:

    frontend /metrics ─┐
                       ├─ ObservationFuser ─→ Planner (predict + invert)
    worker FP metrics ─┘          │
                                  ├─ reactive backlog / SLO-breach terms
                                  ▼
                    cooldown + readiness gate (anti-flap, anti-phantom)
                                  ▼
               VirtualConnector SCALE_KEY ─→ ProcessOperator (spawn/drain)
                                  ▲                  │
                                  └── ready counts ──┘  (operator status)

Two stability mechanisms beyond the planner's own scale-down patience:

- **cooldown/hysteresis** (``SloConfig.cooldown_{up,down}_s``): a scale
  event opens a per-direction quiet period; decisions inside it hold the
  applied target. Asymmetric on purpose — scale-up reacts in one interval,
  scale-down waits out transients.
- **readiness gating**: the operator reports how many replicas are
  *registered on the control plane* (for engine workers that registration
  happens only after AOT warmup — ``engine/main.py`` warms up BEFORE
  joining the plane). While ready < applied target, further scale-up is
  deferred: the capacity is already coming, and stacking decisions during
  a compile cliff is how feedback loops overshoot. Corrections likewise
  read the READY count (``Observation.ready_*``), so a latency spike
  measured against phantom capacity cannot inflate the correction factor.
"""

from __future__ import annotations

import asyncio
import json
import logging
import math
import time
from dataclasses import dataclass, field
from typing import Optional

from dynamo_tpu.autoscale.observe import FusedObservation, ObservationFuser
from dynamo_tpu.autoscale.slo import SloConfig
from dynamo_tpu.planner.perf_interpolation import (
    PerfInterpolator, PerfInterpolator2D,
)
from dynamo_tpu.planner.planner_core import Decision, Planner, PlannerConfig

logger = logging.getLogger("dynamo.autoscale")

#: controller status on the control plane (``dynctl autoscale`` reads it)
AUTOSCALE_STATUS_KEY = "public/autoscale/{namespace}/status"
#: operator-observed fleet state (written by deploy/operator.py)
OPERATOR_STATUS_KEY = "public/operator/{namespace}/status"


def make_planner(slo: SloConfig,
                 prefill_perf: "PerfInterpolator | PerfInterpolator2D",
                 decode_perf: PerfInterpolator,
                 **overrides) -> Planner:
    """Planner parameterized by the governing class's SLO (the strictest
    class sizes the fleet; weaker classes ride its capacity)."""
    gov = slo.governing
    kw = dict(
        ttft_sla_ms=gov.ttft_p95_ms,
        itl_sla_ms=gov.itl_ms,
        adjustment_interval_s=slo.adjustment_interval_s,
        predictor=slo.predictor,
        min_prefill_replicas=slo.min_replicas,
        max_prefill_replicas=slo.max_replicas,
        min_decode_replicas=slo.min_replicas,
        max_decode_replicas=slo.max_replicas)
    kw.update(overrides)
    return Planner(PlannerConfig(**kw), prefill_perf, decode_perf)


async def plane_readiness(plane, namespace: str = "dynamo") -> Optional[dict]:
    """Read the operator's ready counts by planner role from its status
    key → ``{"prefill": n, "decode": n}`` (None when no operator runs)."""
    try:
        raw = await plane.kv_get(OPERATOR_STATUS_KEY.format(
            namespace=namespace))
    except Exception:
        return None
    if not raw:
        return None
    try:
        status = json.loads(raw)
    except ValueError:
        return None
    out: dict[str, int] = {}
    drain_s = float(status.get("drainSecondsTotal", 0.0) or 0.0)
    for svc in (status.get("services") or {}).values():
        role = svc.get("plannerRole")
        if role:
            out[role] = out.get(role, 0) + int(svc.get("ready", 0))
    out["_drain_seconds_total"] = drain_s
    return out


@dataclass
class TickResult:
    """What one controller tick decided and why (tests + status view)."""

    desired: Decision
    applied: bool
    direction: str  # "up" | "down" | "hold"
    reason: str
    fused: Optional[FusedObservation] = None
    ready: Optional[dict] = None
    breaches: dict = field(default_factory=dict)


class AutoscaleController:
    """One tick = observe → predict → decide → gate → actuate."""

    def __init__(self, slo: SloConfig, planner: Planner,
                 source: "ObservationFuser", connector, *,
                 readiness=None, metrics=None, plane=None,
                 namespace: str = "dynamo", now_fn=time.monotonic):
        self.slo = slo
        self.planner = planner
        self.source = source          # async () -> FusedObservation
        self.connector = connector    # async .apply(Decision)
        self.readiness = readiness    # async () -> {"decode": n, ...}|None
        self.plane = plane
        self.namespace = namespace
        self.now = now_fn
        self.applied: Decision = planner.current
        self._last_up: float = float("-inf")
        self._last_down: float = float("-inf")
        self.ticks = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.deferred_for_readiness = 0
        self.deferred_for_compile = 0
        self.held_for_cooldown = 0
        self.last_result: Optional[TickResult] = None
        self._init_metrics(metrics)

    def _init_metrics(self, metrics) -> None:
        """dynamo_autoscale_* families on the host process's registry."""
        if metrics is None:
            self._m_decisions = self._m_desired = None
            self._m_ready = self._m_drain = None
            return
        self._m_decisions = metrics.counter(
            "autoscale_decisions_total",
            "autoscale decisions applied, by direction")
        self._m_desired = metrics.gauge(
            "autoscale_replicas_desired",
            "replica target the controller last applied, by role")
        self._m_ready = metrics.gauge(
            "autoscale_replicas_ready",
            "replicas registered+warm per the operator, by role")
        self._m_drain = metrics.counter(
            "autoscale_drain_seconds",
            "cumulative seconds scale-down victims spent draining "
            "(operator-reported)")
        self._drain_reported = 0.0

    # -- decision core -----------------------------------------------------

    def _cooldown_ok(self, direction: str) -> bool:
        now = self.now()
        last = max(self._last_up, self._last_down)
        window = (self.slo.cooldown_up_s if direction == "up"
                  else self.slo.cooldown_down_s)
        return now - last >= window

    def _clamp(self, n: int, role: str = "decode") -> int:
        """Bound a target by the PLANNER's per-role limits — not the
        SLO-wide min/max, which would override tighter per-role bounds
        passed to make_planner (observed in the flagship drive: a pinned
        2-replica prefill pool silently scaled to slo.min_replicas=4).
        Duck-typed planners whose cfg lacks the per-role fields keep the
        SLO-wide bounds."""
        cfg = self.planner.cfg
        lo = getattr(cfg, f"min_{role}_replicas", None)
        hi = getattr(cfg, f"max_{role}_replicas", None)
        if lo is None or hi is None:
            lo, hi = self.slo.min_replicas, self.slo.max_replicas
        return max(lo, min(hi, n))

    def _breaches(self, fused: FusedObservation) -> dict:
        """Per-class SLO breach check from the interval's TTFT p95s."""
        out = {}
        for cls, p95 in (fused.ttft_p95_ms or {}).items():
            target = self.slo.slo_for(cls).ttft_p95_ms
            if target is not None:
                out[cls] = {"ttft_p95_ms": p95, "target_ms": target,
                            "ok": p95 <= target}
                burn = (fused.slo_burn or {}).get(cls)
                if burn is not None:
                    out[cls]["burn"] = burn
        return out

    async def tick(self) -> TickResult:
        self.ticks += 1
        fused = await self.source()
        ready = await self.readiness() if self.readiness is not None else None
        ready_decode = (ready or {}).get("decode")
        ready_prefill = (ready or {}).get("prefill")

        obs = fused.observation
        if obs is not None:
            # corrections must see REAL capacity: during a scale-up's
            # startup/compile window the live fleet is smaller than the
            # planner's decision, and attributing the latency of N-k
            # replicas to N would inflate the correction factor exactly
            # when the loop is most excitable
            if ready_decode is not None:
                obs.ready_decode = ready_decode
            if ready_prefill is not None:
                obs.ready_prefill = ready_prefill
            self.planner.observe(obs)
        target = self.planner.compute()
        p, d = target.prefill_replicas, target.decode_replicas
        reason = "predicted"

        # reactive backlog term: queue depth the edge rates can't see.
        # Sized against the APPLIED fleet: backlog/replica over the knob
        # means the current fleet is provably behind, however rosy the
        # completion-rate forecast looks.
        if self.slo.backlog_per_replica > 0 and fused.queue_depth > 0:
            need = math.ceil(fused.queue_depth / self.slo.backlog_per_replica)
            if need > d:
                d, reason = need, "backlog"

        # reactive SLO-breach term: a governed class over its TTFT target
        # asks for one replica beyond the applied fleet (bounded: breaches
        # repeat every tick; cooldown spaces the steps). TTFT is prefill-
        # bound in a disaggregated fleet, so when the prefill dimension is
        # actually scalable it steps too — bumping only decode there would
        # grow the wrong pool forever while the breach persists.
        #
        # With the attribution layer's signals present (frontend exports
        # dynamo_slo_burn_rate{class} / dynamo_slo_breach_compile_share),
        # the term distinguishes breach CAUSES (docs/observability.md):
        # - a compile-cliff breach (breached requests' TTFT dominated by
        #   compile) is deferred — the capacity fix is warmup finishing,
        #   which the readiness gate already owns; adding replicas would
        #   stack MORE cold compiles onto the cliff;
        # - a breach whose class is still inside its error budget
        #   (burn < 1) is held — one slow interval is not sustained load;
        # - everything else is a load breach and scales.
        # Frontends predating the signals report neither gauge, which
        # keeps the original breach-always-scales behavior.
        breaches = self._breaches(fused)
        breached = [cls for cls, b in breaches.items() if not b["ok"]]
        if breached:
            burn = fused.slo_burn or {}
            compile_share = fused.breach_compile_share or {}
            compile_cliff = [c for c in breached
                             if compile_share.get(c, 0.0) >= 0.5]
            load = [c for c in breached
                    if c not in compile_cliff
                    and (c not in burn or burn[c] >= 1.0)]
            if load:
                if self.applied.decode_replicas + 1 > d:
                    d = self.applied.decode_replicas + 1
                    reason = "slo_breach"
                cfg = self.planner.cfg
                if (cfg.max_prefill_replicas > cfg.min_prefill_replicas
                        and self.applied.prefill_replicas + 1 > p):
                    p = self.applied.prefill_replicas + 1
                    reason = "slo_breach"
            elif compile_cliff:
                reason = "breach_compile_deferred"
                self.deferred_for_compile += 1
            else:
                reason = "breach_within_budget"
            if not load:
                # deferred/held is NOT "free to shrink": the pre-burn
                # behavior blocked scale-down during any active breach
                # (the breach bump always exceeded the applied fleet),
                # and removing capacity mid-breach — e.g. while a demand
                # forecast dips because a compile cliff collapsed
                # throughput — would deepen the very breach being held
                p = max(p, self.applied.prefill_replicas)
                d = max(d, self.applied.decode_replicas)

        p, d = self._clamp(p, "prefill"), self._clamp(d, "decode")

        # readiness gate: while the last scale-up is still materializing
        # (ready < applied), don't stack another one — the planner would
        # be reacting to capacity that is already on its way. Both
        # dimensions gate independently (a prefill compile cliff must not
        # stack prefill scale-ups any more than a decode one).
        if (ready_decode is not None
                and ready_decode < self.applied.decode_replicas
                and d > self.applied.decode_replicas):
            d = self.applied.decode_replicas
            reason = "deferred_unready"
            self.deferred_for_readiness += 1
        if (ready_prefill is not None
                and ready_prefill < self.applied.prefill_replicas
                and p > self.applied.prefill_replicas):
            p = self.applied.prefill_replicas
            reason = "deferred_unready"
            self.deferred_for_readiness += 1

        direction = ("up" if (d > self.applied.decode_replicas
                              or p > self.applied.prefill_replicas)
                     else "down" if (d < self.applied.decode_replicas
                                     or p < self.applied.prefill_replicas)
                     else "hold")
        applied = False
        if direction != "hold":
            if self._cooldown_ok(direction):
                decision = Decision(p, d)
                await self.connector.apply(decision)
                self.applied = decision
                # keep the planner's internal state consistent with what
                # was actually actuated (its patience/corrections key off
                # self.current)
                self.planner.current = decision
                if direction == "up":
                    self._last_up = self.now()
                    self.scale_ups += 1
                else:
                    self._last_down = self.now()
                    self.scale_downs += 1
                applied = True
                if self._m_decisions is not None:
                    self._m_decisions.inc(direction=direction)
                logger.info("autoscale %s → prefill=%d decode=%d (%s)",
                            direction, p, d, reason)
            else:
                reason = f"cooldown_{direction}"
                self.held_for_cooldown += 1
                self.planner.current = self.applied
        else:
            self.planner.current = self.applied

        result = TickResult(desired=self.applied, applied=applied,
                            direction=direction if applied else "hold",
                            reason=reason, fused=fused, ready=ready,
                            breaches=breaches)
        self.last_result = result
        self._export(result, ready)
        await self._publish_status(result)
        return result

    # -- telemetry ---------------------------------------------------------

    def _export(self, result: TickResult, ready: Optional[dict]) -> None:
        if self._m_desired is None:
            return
        self._m_desired.set(self.applied.decode_replicas, role="decode")
        self._m_desired.set(self.applied.prefill_replicas, role="prefill")
        if ready:
            for role in ("decode", "prefill"):
                if role in ready:
                    self._m_ready.set(ready[role], role=role)
            drain = ready.get("_drain_seconds_total", 0.0)
            if drain > self._drain_reported:
                self._m_drain.inc(drain - self._drain_reported)
                self._drain_reported = drain

    async def _publish_status(self, result: TickResult) -> None:
        if self.plane is None:
            return
        fused = result.fused or FusedObservation()
        obs = fused.observation
        status = {
            "ts": time.time(),
            "desired": {"prefill": self.applied.prefill_replicas,
                        "decode": self.applied.decode_replicas},
            "ready": {k: v for k, v in (result.ready or {}).items()
                      if not k.startswith("_")},
            "queueDepth": fused.queue_depth,
            "workers": fused.workers,
            "requestRate": round(obs.request_rate, 3) if obs else None,
            "slo": {cls: dict(b) for cls, b in result.breaches.items()},
            "lastDecision": {"direction": result.direction,
                             "reason": result.reason,
                             "applied": result.applied},
            "sloBurn": dict(fused.slo_burn or {}),
            "counters": {"ticks": self.ticks, "scaleUps": self.scale_ups,
                         "scaleDowns": self.scale_downs,
                         "deferredUnready": self.deferred_for_readiness,
                         "deferredCompile": self.deferred_for_compile,
                         "heldCooldown": self.held_for_cooldown,
                         "scrapeFailures": getattr(self.source,
                                                   "scrape_failures", 0)},
        }
        try:
            await self.plane.kv_put(
                AUTOSCALE_STATUS_KEY.format(namespace=self.namespace),
                json.dumps(status).encode())
        except Exception:
            logger.warning("autoscale status publish failed", exc_info=True)


class AutoscaleRunner:
    """Wall-clock loop around the controller (PlannerRunner's shape: a
    tick exception is logged and the loop keeps going — one bad scrape
    must not abandon the fleet)."""

    def __init__(self, controller: AutoscaleController,
                 interval_s: Optional[float] = None):
        self.controller = controller
        self.interval = interval_s or controller.slo.adjustment_interval_s
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()
        self.tick_errors = 0

    async def start(self) -> "AutoscaleRunner":
        self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    async def stop(self) -> None:
        self._stop.set()
        if self._task:
            await self._task

    async def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                await self.controller.tick()
            except Exception:
                self.tick_errors += 1
                logger.exception("autoscale tick failed")
            try:
                await asyncio.wait_for(self._stop.wait(), self.interval)
            except asyncio.TimeoutError:
                pass
