"""Self-contained control plane with etcd + NATS semantics.

The reference runtime leans on two external services (SURVEY.md §2.1): etcd for
discovery/leases/watches (ref: lib/runtime/src/transports/etcd.rs:35) and NATS
for the request plane, events, queues and object store (ref: transports/
nats.rs:48,426). A TPU-VM pod should not need either, so this module provides
one service — ``dynctl`` — with both semantic sets:

- **KV + leases + prefix watches** (etcd): ``kv_put/kv_create/kv_get/
  kv_get_prefix/kv_delete``, leases with TTL + keepalive whose expiry deletes
  attached keys and fires watch delete events.
- **Pub/sub + request/reply** (NATS core): subjects with optional queue
  groups; ``request`` raises :class:`NoRespondersError` when nothing serves
  the subject — the same signal the reference uses for instant fault
  detection (ref: pipeline/network/egress/push_router.rs:229).
- **Durable streams + object store** (NATS JetStream): append-only logs with
  consumer offsets (KV events ride these) and a bucket/name byte store
  (radix snapshots).

Two interchangeable implementations: :class:`LocalControlPlane` (pure
in-process asyncio — used single-process and as the server's core) and
:class:`RemoteControlPlane` (TCP client to a :class:`ControlPlaneServer`).
Because the server *wraps* a LocalControlPlane, cross-process behavior is
identical to in-process behavior by construction.
"""

from __future__ import annotations

import abc
import asyncio
import logging
import os
import random
import time

import msgpack
from collections import deque
from dataclasses import dataclass, field
from typing import AsyncIterator, Awaitable, Callable, Optional

from dynamo_tpu.runtime.chaos import get_chaos
from dynamo_tpu.runtime.codec import read_frame, write_frame

logger = logging.getLogger("dynamo.control_plane")

DEFAULT_LEASE_TTL = 10.0
SWEEP_INTERVAL = 1.0
STREAM_MAX_LEN = 65536  # per-stream ring buffer cap
# In-band stream discontinuity marker (see RemoteControlPlane._replay): real
# stream seqs are >= 1, so a negative seq can never collide with one.
EPOCH_MARKER_SEQ = -1


class NoRespondersError(Exception):
    """No service instance is listening on the requested subject."""


class ControlPlaneClosed(Exception):
    pass


@dataclass(frozen=True)
class WatchEvent:
    type: str  # "put" | "delete"
    key: str
    value: bytes = b""


class Watch:
    """Prefix watch: a snapshot plus a live event queue."""

    def __init__(self, snapshot: dict[str, bytes], queue: "asyncio.Queue[Optional[WatchEvent]]", cancel):
        self.snapshot = snapshot
        self._queue = queue
        self._cancel = cancel

    def __aiter__(self) -> AsyncIterator[WatchEvent]:
        return self._iter()

    async def _iter(self):
        while True:
            ev = await self._queue.get()
            if ev is None:
                return
            yield ev

    async def cancel(self) -> None:
        await self._cancel()


class Subscription:
    """Pub/sub subscription handle yielding ``(subject, payload)``."""

    def __init__(self, queue: "asyncio.Queue[Optional[tuple[str, bytes]]]", cancel):
        self._queue = queue
        self._cancel = cancel

    def __aiter__(self):
        return self._iter()

    async def _iter(self):
        while True:
            item = await self._queue.get()
            if item is None:
                return
            yield item

    async def cancel(self) -> None:
        await self._cancel()


class StreamSub:
    """Durable-stream subscription yielding ``(seq, payload)`` from a start offset."""

    def __init__(self, queue: "asyncio.Queue[Optional[tuple[int, bytes]]]", cancel):
        self._queue = queue
        self._cancel = cancel

    def __aiter__(self):
        return self._iter()

    async def _iter(self):
        while True:
            item = await self._queue.get()
            if item is None:
                return
            yield item

    async def cancel(self) -> None:
        await self._cancel()


ServiceHandler = Callable[[bytes], Awaitable[bytes]]


class ControlPlane(abc.ABC):
    """Abstract control-plane client surface. All methods are coroutine-safe."""

    # -- KV (etcd semantics) --
    @abc.abstractmethod
    async def kv_put(self, key: str, value: bytes, lease_id: Optional[int] = None) -> None: ...

    @abc.abstractmethod
    async def kv_create(self, key: str, value: bytes, lease_id: Optional[int] = None) -> bool:
        """Create-if-absent; returns False when the key already exists."""

    @abc.abstractmethod
    async def kv_get(self, key: str) -> Optional[bytes]: ...

    @abc.abstractmethod
    async def kv_get_prefix(self, prefix: str) -> dict[str, bytes]: ...

    @abc.abstractmethod
    async def kv_delete(self, key: str) -> int: ...

    @abc.abstractmethod
    async def kv_delete_prefix(self, prefix: str) -> int: ...

    @abc.abstractmethod
    async def watch_prefix(self, prefix: str) -> Watch: ...

    # -- Leases --
    @abc.abstractmethod
    async def lease_create(self, ttl: float = DEFAULT_LEASE_TTL) -> int: ...

    @abc.abstractmethod
    async def lease_keepalive(self, lease_id: int) -> bool: ...

    @abc.abstractmethod
    async def lease_revoke(self, lease_id: int) -> None: ...

    # -- Pub/sub + request/reply (NATS semantics) --
    @abc.abstractmethod
    async def publish(self, subject: str, payload: bytes) -> None: ...

    @abc.abstractmethod
    async def subscribe(self, subject: str, queue_group: Optional[str] = None) -> Subscription: ...

    @abc.abstractmethod
    async def request(self, subject: str, payload: bytes, timeout: float = 30.0) -> bytes: ...

    @abc.abstractmethod
    async def serve(self, subject: str, handler: ServiceHandler):
        """Register a request handler; returns an awaitable-cancel handle.

        Multiple registrations on one subject form an implicit queue group:
        ``request`` round-robins across them (NATS service semantics)."""

    # -- Work queues (NatsQueue semantics, ref: transports/nats.rs:426 —
    #    the global prefill queue rides this) --
    @abc.abstractmethod
    async def queue_push(self, queue: str, payload: bytes) -> None: ...

    @abc.abstractmethod
    async def queue_pop(self, queue: str, timeout: float = 30.0) -> Optional[bytes]:
        """Pop one item; blocks up to ``timeout``; None when nothing arrived.
        Each item is delivered to exactly one popper (work-queue semantics)."""

    @abc.abstractmethod
    async def queue_depth(self, queue: str) -> int: ...

    # -- Durable streams (JetStream semantics) --
    @abc.abstractmethod
    async def stream_publish(self, stream: str, payload: bytes) -> int: ...

    @abc.abstractmethod
    async def stream_subscribe(self, stream: str, start_seq: int = 0) -> StreamSub: ...

    @abc.abstractmethod
    async def stream_last_seq(self, stream: str) -> int: ...

    @abc.abstractmethod
    async def stream_first_seq(self, stream: str) -> int:
        """Oldest seq still retained (ring truncation floor). A consumer whose
        last applied seq is < first_seq-1 has provably missed events and must
        resync (ref: JetStream stream FirstSeq, kv_router/subscriber.rs:30-65)."""

    # -- Object store --
    @abc.abstractmethod
    async def object_put(self, bucket: str, name: str, data: bytes) -> None: ...

    @abc.abstractmethod
    async def object_get(self, bucket: str, name: str) -> Optional[bytes]: ...

    @abc.abstractmethod
    async def object_delete(self, bucket: str, name: str) -> None: ...

    @abc.abstractmethod
    async def close(self) -> None: ...


# --------------------------------------------------------------------------
# Local (in-process) implementation
# --------------------------------------------------------------------------


@dataclass
class _Lease:
    id: int
    ttl: float
    deadline: float
    keys: set[str] = field(default_factory=set)
    owner: Optional[object] = None  # connection tag for revoke-on-disconnect


@dataclass
class _ServiceReg:
    subject: str
    handler: ServiceHandler
    owner: Optional[object] = None


def _subject_matches(pattern: str, subject: str) -> bool:
    """NATS-style: exact match, or trailing ``>`` matches any suffix."""
    if pattern.endswith(">"):
        return subject.startswith(pattern[:-1])
    return pattern == subject


class _HubHist:
    """Tiny fixed-bucket latency histogram for hub self-instrumentation —
    runtime.metrics.Histogram carries labels/locks this single-loop hot
    path does not need. Rendered as ``dynamo_hub_publish_seconds`` by the
    metrics aggregator (metrics/main.py)."""

    BUCKETS = (1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2, 1e-1)

    __slots__ = ("counts", "sum", "count")

    def __init__(self):
        self.counts = [0] * (len(self.BUCKETS) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        self.sum += v
        self.count += 1
        for i, b in enumerate(self.BUCKETS):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def to_dict(self) -> dict:
        cum, buckets = 0, {}
        for i, b in enumerate(self.BUCKETS):
            cum += self.counts[i]
            buckets[str(b)] = cum
        buckets["+Inf"] = self.count
        return {"count": self.count, "sum": self.sum, "buckets": buckets}


class LocalControlPlane(ControlPlane):
    """In-process control plane; also the core of :class:`ControlPlaneServer`."""

    def __init__(self, stream_max_len: int = STREAM_MAX_LEN):
        #: identifies this hub incarnation: stream seqs are only comparable
        #: within one epoch (clients resume from 0 after a hub restart)
        self.epoch = f"{random.getrandbits(64):016x}"
        self.stream_max_len = stream_max_len
        self._kv: dict[str, bytes] = {}
        self._key_lease: dict[str, int] = {}
        self._leases: dict[int, _Lease] = {}
        self._next_lease = int(time.time() * 1000) << 16 | random.getrandbits(16)
        self._watches: list[tuple[str, asyncio.Queue]] = []
        self._subs: list[tuple[str, Optional[str], asyncio.Queue]] = []
        self._services: list[_ServiceReg] = []
        self._rr: dict[str, int] = {}
        self._streams: dict[str, tuple[int, list[tuple[int, bytes]]]] = {}  # first_seq offset handling
        self._stream_subs: dict[str, list[asyncio.Queue]] = {}
        self._queues: dict[str, "deque[bytes]"] = {}
        self._queue_waiters: dict[str, "deque[asyncio.Future]"] = {}
        self._objects: dict[tuple[str, str], bytes] = {}
        self._closed = False
        self._sweeper: Optional[asyncio.Task] = None
        #: hub self-instrumentation (docs/observability.md): per-op event
        #: counters + event-path publish latency, the measured series
        #: behind the fleet-bench batching ceiling (docs/PERF_NOTES.md) —
        #: read via hub_stats() / the `hub_stats` wire op
        self.hub_events: dict[str, int] = {}
        self.hub_publish = _HubHist()
        #: per-stream entries dropped off the ring cap — a consumer
        #: further behind than this sees a gap and must resync
        self.hub_stream_truncated: dict[str, int] = {}
        #: resync requests observed (publishes on the kv_resync.* subject
        #: — the literal prefix is a wire constant, router/protocols.py's
        #: KV_RESYNC_SUBJECT; importing it here would cycle the packages)
        self.hub_resyncs_requested = 0

    def _ensure_sweeper(self):
        if self._sweeper is None or self._sweeper.done():
            self._sweeper = asyncio.get_running_loop().create_task(self._sweep_loop())

    async def _sweep_loop(self):
        try:
            while not self._closed:
                await asyncio.sleep(SWEEP_INTERVAL)
                now = time.monotonic()
                expired = [l.id for l in self._leases.values() if l.deadline < now]
                for lid in expired:
                    logger.info("lease %x expired", lid)
                    await self.lease_revoke(lid)
        except asyncio.CancelledError:
            pass

    def _hub_count(self, kind: str) -> None:
        self.hub_events[kind] = self.hub_events.get(kind, 0) + 1

    async def hub_stats(self) -> dict:
        """Event counters + publish latency for dynctl top and the metrics
        aggregator's dynamo_hub_* series — plus per-stream health (last
        seq / first retained seq / entries truncated off the ring) and
        the resync-request count, so the `dynctl top` hub footer shows
        whether the KV event stream is outrunning its consumers."""
        streams = {}
        for name, (seq, entries) in self._streams.items():
            streams[name] = {
                "last_seq": seq,
                "first_seq": entries[0][0] if entries else seq + 1,
                "truncated": self.hub_stream_truncated.get(name, 0),
            }
        return {"epoch": self.epoch, "events": dict(self.hub_events),
                "publish_seconds": self.hub_publish.to_dict(),
                "streams": streams,
                "resyncs_requested": self.hub_resyncs_requested}

    # -- KV --
    def _notify(self, ev: WatchEvent):
        for prefix, q in self._watches:
            if ev.key.startswith(prefix):
                q.put_nowait(ev)

    async def kv_put(self, key, value, lease_id=None):
        self._hub_count("kv_put")
        self._kv[key] = value
        self._attach_lease(key, lease_id)
        self._notify(WatchEvent("put", key, value))

    def _attach_lease(self, key: str, lease_id: Optional[int]):
        old = self._key_lease.pop(key, None)
        if old is not None and old in self._leases:
            self._leases[old].keys.discard(key)
        if lease_id is not None:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise ValueError(f"unknown lease {lease_id:#x}")
            lease.keys.add(key)
            self._key_lease[key] = lease_id

    async def kv_create(self, key, value, lease_id=None) -> bool:
        if key in self._kv:
            return False
        await self.kv_put(key, value, lease_id)
        return True

    async def kv_get(self, key):
        return self._kv.get(key)

    async def kv_get_prefix(self, prefix):
        return {k: v for k, v in self._kv.items() if k.startswith(prefix)}

    async def kv_delete(self, key) -> int:
        self._hub_count("kv_delete")
        if key in self._kv:
            del self._kv[key]
            self._attach_lease(key, None)
            self._notify(WatchEvent("delete", key))
            return 1
        return 0

    async def kv_delete_prefix(self, prefix) -> int:
        keys = [k for k in self._kv if k.startswith(prefix)]
        for k in keys:
            await self.kv_delete(k)
        return len(keys)

    async def watch_prefix(self, prefix) -> Watch:
        q: asyncio.Queue = asyncio.Queue()
        entry = (prefix, q)
        self._watches.append(entry)
        snapshot = await self.kv_get_prefix(prefix)

        async def cancel():
            if entry in self._watches:
                self._watches.remove(entry)
            q.put_nowait(None)

        return Watch(snapshot, q, cancel)

    # -- Leases --
    async def lease_create(self, ttl=DEFAULT_LEASE_TTL, owner=None) -> int:
        self._ensure_sweeper()
        self._next_lease += 1
        lid = self._next_lease
        self._leases[lid] = _Lease(lid, ttl, time.monotonic() + ttl, owner=owner)
        return lid

    async def lease_keepalive(self, lease_id) -> bool:
        lease = self._leases.get(lease_id)
        if lease is None:
            return False
        lease.deadline = time.monotonic() + lease.ttl
        return True

    async def lease_revoke(self, lease_id):
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return
        for key in list(lease.keys):
            await self.kv_delete(key)

    async def revoke_owned(self, owner):
        """Drop every lease/service/sub owned by a disconnected remote client."""
        for lid in [l.id for l in self._leases.values() if l.owner is owner]:
            await self.lease_revoke(lid)
        self._services = [s for s in self._services if s.owner is not owner]

    # -- Pub/sub --
    async def publish(self, subject, payload):
        self._hub_count("publish")
        if subject.startswith("kv_resync"):
            self.hub_resyncs_requested += 1
        chaos = get_chaos()
        if chaos is not None:
            await chaos.pre("plane.publish")
            if chaos.should_drop("plane.publish"):
                return  # message loss: subscribers simply never see it
        t0 = time.perf_counter()
        groups: dict[str, list[asyncio.Queue]] = {}
        for pattern, qg, q in self._subs:
            if _subject_matches(pattern, subject):
                if qg is None:
                    q.put_nowait((subject, payload))
                else:
                    groups.setdefault(qg, []).append(q)
        for qs in groups.values():
            random.choice(qs).put_nowait((subject, payload))
        self.hub_publish.observe(time.perf_counter() - t0)

    async def subscribe(self, subject, queue_group=None) -> Subscription:
        q: asyncio.Queue = asyncio.Queue()
        entry = (subject, queue_group, q)
        self._subs.append(entry)

        async def cancel():
            if entry in self._subs:
                self._subs.remove(entry)
            q.put_nowait(None)

        return Subscription(q, cancel)

    # -- Request/reply --
    async def request(self, subject, payload, timeout=30.0) -> bytes:
        self._hub_count("request")
        regs = [s for s in self._services if _subject_matches(s.subject, subject)]
        if not regs:
            raise NoRespondersError(subject)
        idx = self._rr.get(subject, 0)
        self._rr[subject] = idx + 1
        reg = regs[idx % len(regs)]
        return await asyncio.wait_for(reg.handler(payload), timeout)

    async def serve(self, subject, handler, owner=None):
        reg = _ServiceReg(subject, handler, owner)
        self._services.append(reg)

        async def cancel():
            if reg in self._services:
                self._services.remove(reg)

        return cancel

    def has_responder(self, subject: str) -> bool:
        return any(_subject_matches(s.subject, subject) for s in self._services)

    # -- Work queues --
    QUEUE_MAX_LEN = 65536  # oldest tickets dropped past this (cap like streams)

    async def queue_push(self, queue, payload) -> None:
        self._hub_count("queue_push")
        waiters = self._queue_waiters.get(queue)
        while waiters:
            fut = waiters.popleft()
            if not fut.done():  # hand straight to a blocked popper
                fut.set_result(payload)
                return
        q = self._queues.setdefault(queue, deque())
        q.append(payload)
        while len(q) > self.QUEUE_MAX_LEN:
            q.popleft()

    async def queue_pop(self, queue, timeout: float = 30.0) -> Optional[bytes]:
        q = self._queues.get(queue)
        if q:
            return q.popleft()
        fut = asyncio.get_running_loop().create_future()
        waiters = self._queue_waiters.setdefault(queue, deque())
        waiters.append(fut)
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            return None
        finally:
            # a timed-out waiter must not linger until the next push skims it
            try:
                waiters.remove(fut)
            except ValueError:
                pass

    async def queue_depth(self, queue) -> int:
        return len(self._queues.get(queue, ()))

    # -- Durable streams --
    async def stream_publish(self, stream, payload) -> int:
        self._hub_count("stream_publish")
        chaos = get_chaos()
        if chaos is not None:
            await chaos.pre("plane.publish")
            if chaos.should_drop("plane.publish"):
                # lost BEFORE the stream assigns a seq: no gap for the
                # consumer's sequence check to see — the silent-drift
                # shape the KV audit plane exists to catch
                # (docs/observability.md "KV audit")
                seq, _ = self._streams.get(stream, (0, []))
                return seq
        t0 = time.perf_counter()
        seq, entries = self._streams.get(stream, (0, []))
        seq += 1
        entries.append((seq, payload))
        if len(entries) > self.stream_max_len:
            dropped = len(entries) - self.stream_max_len
            self.hub_stream_truncated[stream] = (
                self.hub_stream_truncated.get(stream, 0) + dropped)
            entries[:] = entries[-self.stream_max_len:]
        self._streams[stream] = (seq, entries)
        for q in self._stream_subs.get(stream, []):
            q.put_nowait((seq, payload))
        self.hub_publish.observe(time.perf_counter() - t0)
        return seq

    async def stream_subscribe(self, stream, start_seq=0) -> StreamSub:
        q: asyncio.Queue = asyncio.Queue()
        _, entries = self._streams.get(stream, (0, []))
        for seq, payload in entries:
            if seq > start_seq:
                q.put_nowait((seq, payload))
        self._stream_subs.setdefault(stream, []).append(q)

        async def cancel():
            subs = self._stream_subs.get(stream, [])
            if q in subs:
                subs.remove(q)
            q.put_nowait(None)

        return StreamSub(q, cancel)

    async def stream_last_seq(self, stream) -> int:
        seq, _ = self._streams.get(stream, (0, []))
        return seq

    async def stream_first_seq(self, stream) -> int:
        seq, entries = self._streams.get(stream, (0, []))
        return entries[0][0] if entries else seq + 1

    async def get_epoch(self) -> str:
        return self.epoch

    # -- persistence (dynctl --persist) ---------------------------------
    #: stream entries retained in a snapshot — consumers further behind
    #: resync via the gap protocol (indexer stream_first_seq check), so a
    #: bounded snapshot is principled, not lossy-by-accident
    PERSIST_STREAM_TAIL = 4096

    def dump_state(self) -> bytes:
        """Durable subset of hub state. LEASED keys are excluded: their
        owners died with the old process and re-register under fresh
        leases — persisting them would resurrect ghost instances. The
        epoch is preserved so stream seqs stay comparable across the
        restart (consumers resume WITHOUT a false gap)."""
        kv = {k: v for k, v in self._kv.items() if k not in self._key_lease}
        streams = {
            name: [seq, [list(e) for e in entries[-self.PERSIST_STREAM_TAIL:]]]
            for name, (seq, entries) in self._streams.items()
        }
        objects = [[b, n, data] for (b, n), data in self._objects.items()]
        return msgpack.packb({"v": 1, "epoch": self.epoch, "kv": kv,
                              "streams": streams, "objects": objects})

    def load_state(self, data: bytes) -> None:
        d = msgpack.unpackb(data, raw=False)
        self.epoch = d["epoch"]
        self._kv.update(d.get("kv") or {})
        for name, (seq, entries) in (d.get("streams") or {}).items():
            self._streams[name] = (seq, [tuple(e) for e in entries])
        for b, n, obj in d.get("objects") or []:
            self._objects[(b, n)] = obj

    def replace_state(self, data: bytes) -> None:
        """Standby replication: mirror a primary's durable state wholesale
        (a standby serves no clients, so there are no watches/subs to
        notify — deleted keys must vanish, hence clear-then-load)."""
        self._kv.clear()
        self._streams.clear()
        self._objects.clear()
        self.load_state(data)

    # -- Object store --
    async def object_put(self, bucket, name, data):
        self._objects[(bucket, name)] = data

    async def object_get(self, bucket, name):
        return self._objects.get((bucket, name))

    async def object_delete(self, bucket, name):
        self._objects.pop((bucket, name), None)

    async def close(self):
        self._closed = True
        if self._sweeper:
            self._sweeper.cancel()
        for _, q in self._watches:
            q.put_nowait(None)
        for _, _, q in self._subs:
            q.put_nowait(None)
        for qs in self._stream_subs.values():
            for q in qs:
                q.put_nowait(None)
        for waiters in self._queue_waiters.values():
            for fut in waiters:
                if not fut.done():
                    fut.set_result(None)


# --------------------------------------------------------------------------
# TCP server + remote client
# --------------------------------------------------------------------------


class ControlPlaneServer:
    """``dynctl``: exposes a LocalControlPlane over TCP to many processes."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 persist_path: Optional[str] = None,
                 persist_interval: float = 5.0,
                 standby_of: Optional[str] = None,
                 takeover_after: float = 6.0,
                 replicate_interval: float = 1.0):
        self.core = LocalControlPlane()
        self._host = host
        self._port = port
        self._server: Optional[asyncio.base_events.Server] = None
        self._conns: set["_ServerConn"] = set()
        #: durable-state file (ref role: etcd's WAL + JetStream file store —
        #: discovery keys, object store, stream tails survive a hub restart;
        #: leases deliberately do NOT). None = in-memory only.
        self._persist_path = persist_path
        self._persist_interval = persist_interval
        self._persist_task: Optional[asyncio.Task] = None
        #: warm standby (ref role: etcd replication / clustered NATS —
        #: lib/runtime/src/transports/etcd.rs:35-770 rides an HA etcd
        #: cluster; dynctl gets a 2-node primary/standby analog): while
        #: ``standby_of`` is set the server rejects client ops, mirrors the
        #: primary's durable state every ``replicate_interval`` s, and
        #: promotes itself after ``takeover_after`` s of primary silence.
        self._standby_of = standby_of
        self._takeover_after = takeover_after
        self._replicate_interval = replicate_interval
        self._standby_task: Optional[asyncio.Task] = None
        self._fence_task: Optional[asyncio.Task] = None
        self.is_standby = standby_of is not None

    @property
    def address(self) -> str:
        return f"{self._host}:{self._port}"

    async def start(self) -> str:
        if self._persist_path and os.path.exists(self._persist_path):
            try:
                with open(self._persist_path, "rb") as f:
                    self.core.load_state(f.read())
                logger.info("control plane state restored from %s (epoch %s)",
                            self._persist_path, self.core.epoch)
            except Exception:
                logger.exception("state restore failed; starting fresh")
        self._server = await asyncio.start_server(self._on_conn, self._host, self._port)
        self._port = self._server.sockets[0].getsockname()[1]
        if self._persist_path:
            self._persist_task = asyncio.get_running_loop().create_task(
                self._persist_loop())
        if self.is_standby:
            self._standby_task = asyncio.get_running_loop().create_task(
                self._standby_loop())
        logger.info("control plane listening on %s%s", self.address,
                    " (standby)" if self.is_standby else "")
        return self.address

    async def _standby_loop(self):
        """Mirror the primary until it goes silent, then promote."""
        last_ok = time.monotonic()
        host, _, port = self._standby_of.rpartition(":")
        host, port = host or "127.0.0.1", int(port)
        reader = writer = None
        rid = 0
        try:
            while self.is_standby:
                try:
                    if writer is None:
                        reader, writer = await asyncio.wait_for(
                            asyncio.open_connection(host, port), 5.0)
                    rid += 1
                    await write_frame(writer, {"t": "req", "id": rid,
                                               "op": "dump_state"})
                    # private conn: the only traffic is our own responses
                    msg = await asyncio.wait_for(read_frame(reader), 10.0)
                    if not (msg.get("t") == "res" and msg.get("ok")):
                        raise RuntimeError(msg.get("detail", "pull failed"))
                    self.core.replace_state(msg["value"])
                    last_ok = time.monotonic()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    if writer is not None:
                        try:
                            writer.close()
                        except Exception:
                            pass
                    reader = writer = None
                    if time.monotonic() - last_ok > self._takeover_after:
                        self._promote()
                        return
                await asyncio.sleep(self._replicate_interval)
        except asyncio.CancelledError:
            pass
        finally:
            if writer is not None:
                try:
                    writer.close()
                except Exception:
                    pass

    def _promote(self):
        """Standby → primary. The replicated state may lag the dead primary
        by up to one replicate interval, so old-epoch stream seqs can sit
        AHEAD of our counters — a fresh epoch forces every client to resume
        streams from 0 and resync through the gap protocol (indexer
        snapshot restore) instead of silently skipping rolled-back entries."""
        self.core.epoch = f"{random.getrandbits(64):016x}"
        self.is_standby = False
        logger.warning("standby promoted to primary (epoch %s)",
                       self.core.epoch)
        # fence the OLD primary: if it was merely paused/partitioned (not
        # dead) it would otherwise keep serving its connected clients
        # forever — split brain. Keep probing its address; on contact,
        # demote it into OUR standby.
        self._fence_task = asyncio.get_running_loop().create_task(
            self._fence_old_primary(self._standby_of))

    async def _fence_old_primary(self, old_addr: str):
        host, _, port = old_addr.rpartition(":")
        host, port = host or "127.0.0.1", int(port)
        while True:
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(host, port), 5.0)
                try:
                    await write_frame(writer, {"t": "req", "id": 1,
                                               "op": "demote",
                                               "port": self._port,
                                               "epoch": self.core.epoch})
                    msg = await asyncio.wait_for(read_frame(reader), 10.0)
                    if msg.get("ok"):
                        logger.warning("old primary %s demoted into standby",
                                       old_addr)
                        return
                finally:
                    try:
                        writer.close()
                    except Exception:
                        pass
            except asyncio.CancelledError:
                raise
            except Exception:
                pass
            await asyncio.sleep(max(self._replicate_interval * 2, 1.0))

    async def demote(self, new_primary: str, epoch: Optional[str] = None):
        """A newer primary exists (it fenced us): reject clients from now
        on — closing their conns makes them fail over within one reconnect
        cycle — and fall in line as the new primary's standby.

        Trust model: like every other op on this plane (any client may
        kv_delete_prefix the world), demote assumes a trusted network — the
        reference's etcd/NATS deployments carry the same assumption inside
        the cluster. Two guards bound the blast radius of a stray frame:
        the epoch must differ from ours (a real fencer always promoted
        under a fresh one), and a demotion toward a dead/bogus peer
        self-heals — the standby loop re-promotes after ``takeover_after``
        of failed pulls."""
        if self.is_standby:
            return
        if epoch is not None and epoch == self.core.epoch:
            logger.warning("ignoring demote carrying our own epoch")
            return
        logger.warning("demoted: %s took over while we were unreachable; "
                       "becoming its standby", new_primary)
        self.is_standby = True
        self._standby_of = new_primary
        for conn in list(self._conns):
            try:
                conn.writer.close()
            except Exception:
                pass
        if self._standby_task is None or self._standby_task.done():
            self._standby_task = asyncio.get_running_loop().create_task(
                self._standby_loop())

    def _write_state(self, data: bytes) -> None:
        tmp = f"{self._persist_path}.tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, self._persist_path)  # atomic: never a torn snapshot

    async def _persist_loop(self):
        try:
            while True:
                await asyncio.sleep(self._persist_interval)
                try:
                    # dump on the LOOP thread: the core's dicts are mutated
                    # by loop-thread handlers, so iterating them off-thread
                    # races ("dict changed size"); only the file IO moves
                    # to a worker
                    data = self.core.dump_state()
                    await asyncio.to_thread(self._write_state, data)
                except Exception:
                    logger.exception("state snapshot failed; retrying next tick")
        except asyncio.CancelledError:
            pass

    async def stop(self):
        if self._fence_task:
            self._fence_task.cancel()
            try:
                await self._fence_task
            except (asyncio.CancelledError, Exception):
                pass
        if self._standby_task:
            self._standby_task.cancel()
            try:
                await self._standby_task
            except (asyncio.CancelledError, Exception):
                pass
        if self._persist_task:
            self._persist_task.cancel()
            try:
                # an in-flight to_thread write can't be cancelled mid-write;
                # await it so it can't land AFTER (and clobber) the final
                # flush below
                await self._persist_task
            except (asyncio.CancelledError, Exception):
                pass
        if self._persist_path:
            try:
                # final flush: clean shutdown loses nothing
                self._write_state(self.core.dump_state())
            except Exception:
                logger.exception("final state snapshot failed")
        if self._server:
            self._server.close()
        for conn in list(self._conns):
            try:
                conn.writer.close()
            except Exception:
                pass
        if self._server:
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5.0)
            except asyncio.TimeoutError:
                logger.warning("control-plane server connections did not drain")
        await self.core.close()

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        conn = _ServerConn(self.core, reader, writer, server=self)
        self._conns.add(conn)
        try:
            await conn.run()
        finally:
            self._conns.discard(conn)


class _ServerConn:
    """Per-client server-side connection: dispatches ops onto the core plane."""

    def __init__(self, core: LocalControlPlane, reader, writer, server=None):
        self.core = core
        self.reader = reader
        self.writer = writer
        self.server = server
        self._wlock = asyncio.Lock()
        self._watch_tasks: dict[int, asyncio.Task] = {}
        self._watch_handles: dict[int, Watch] = {}
        self._sub_tasks: dict[int, asyncio.Task] = {}
        self._sub_handles: dict[int, object] = {}
        self._svc_cancels: dict[int, Callable] = {}
        self._pending_svc: dict[int, asyncio.Future] = {}
        self._next_rid = 0

    async def _send(self, obj):
        async with self._wlock:
            await write_frame(self.writer, obj)

    async def run(self):
        try:
            while True:
                try:
                    msg = await read_frame(self.reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                t = msg.get("t")
                if t == "req":
                    asyncio.get_running_loop().create_task(self._handle_req(msg))
                elif t == "svc_res":
                    fut = self._pending_svc.pop(msg["rid"], None)
                    if fut and not fut.done():
                        if msg.get("ok", False):
                            fut.set_result(msg.get("payload", b""))
                        else:
                            fut.set_exception(RuntimeError(msg.get("error", "remote handler error")))
        finally:
            await self._cleanup()

    async def _cleanup(self):
        for task in list(self._watch_tasks.values()) + list(self._sub_tasks.values()):
            task.cancel()
        for h in self._watch_handles.values():
            await h.cancel()
        for h in self._sub_handles.values():
            await h.cancel()  # type: ignore[attr-defined]
        for cancel in self._svc_cancels.values():
            await cancel()
        for fut in self._pending_svc.values():
            if not fut.done():
                fut.set_exception(ConnectionError("client disconnected"))
        await self.core.revoke_owned(self)
        try:
            self.writer.close()
        except Exception:
            pass

    async def _handle_req(self, msg):
        rid = msg["id"]
        op = msg["op"]
        if op == "demote" and self.server is not None:
            # fencing from a promoted standby (see _fence_old_primary);
            # its reachable address = the conn's source IP + its port
            peer = self.writer.get_extra_info("peername") or ("127.0.0.1",)
            await self._send({"t": "res", "id": rid, "ok": True,
                              "value": None})
            await self.server.demote(f"{peer[0]}:{msg['port']}",
                                     epoch=msg.get("epoch"))
            return
        # a standby mirrors state but serves no clients: reject every op so
        # a multi-address RemoteControlPlane fails over to the primary
        # (dump_state stays open — it is how replication reads us/peers)
        if (self.server is not None and self.server.is_standby
                and op != "dump_state"):
            await self._send({"t": "res", "id": rid, "ok": False,
                              "error": "standby",
                              "detail": "hub is a standby replica"})
            return
        try:
            result = await self._dispatch(op, msg)
            await self._send({"t": "res", "id": rid, "ok": True, "value": result})
        except NoRespondersError as e:
            await self._send({"t": "res", "id": rid, "ok": False, "error": "no_responders", "detail": str(e)})
        except Exception as e:
            logger.exception("control-plane op %s failed", op)
            await self._send({"t": "res", "id": rid, "ok": False, "error": "error", "detail": repr(e)})

    async def _dispatch(self, op, m):
        core = self.core
        if op == "kv_put":
            await core.kv_put(m["key"], m["value"], m.get("lease"))
        elif op == "kv_create":
            return await core.kv_create(m["key"], m["value"], m.get("lease"))
        elif op == "kv_get":
            return core._kv.get(m["key"])
        elif op == "kv_get_prefix":
            return await core.kv_get_prefix(m["prefix"])
        elif op == "kv_delete":
            return await core.kv_delete(m["key"])
        elif op == "kv_delete_prefix":
            return await core.kv_delete_prefix(m["prefix"])
        elif op == "lease_create":
            return await core.lease_create(m.get("ttl", DEFAULT_LEASE_TTL), owner=self)
        elif op == "lease_keepalive":
            return await core.lease_keepalive(m["lease"])
        elif op == "lease_revoke":
            await core.lease_revoke(m["lease"])
        elif op == "publish":
            await core.publish(m["subject"], m["payload"])
        elif op == "request":
            return await core.request(m["subject"], m["payload"], m.get("req_timeout", 30.0))
        elif op == "watch":
            return await self._start_watch(m["wid"], m["prefix"])
        elif op == "watch_cancel":
            await self._stop_watch(m["wid"])
        elif op == "subscribe":
            await self._start_sub(m["sid"], m["subject"], m.get("queue_group"))
        elif op == "sub_cancel":
            await self._stop_sub(m["sid"])
        elif op == "serve":
            await self._start_serve(m["svc_id"], m["subject"])
        elif op == "serve_cancel":
            cancel = self._svc_cancels.pop(m["svc_id"], None)
            if cancel:
                await cancel()
        elif op == "epoch":
            return core.epoch
        elif op == "hub_stats":
            return await core.hub_stats()
        elif op == "dump_state":
            return core.dump_state()
        elif op == "queue_push":
            await core.queue_push(m["queue"], m["payload"])
        elif op == "queue_pop":
            return await core.queue_pop(m["queue"], m.get("pop_timeout", 30.0))
        elif op == "queue_depth":
            return await core.queue_depth(m["queue"])
        elif op == "stream_publish":
            return await core.stream_publish(m["stream"], m["payload"])
        elif op == "stream_subscribe":
            await self._start_stream_sub(m["sid"], m["stream"], m.get("start_seq", 0))
        elif op == "stream_last_seq":
            return await core.stream_last_seq(m["stream"])
        elif op == "stream_first_seq":
            return await core.stream_first_seq(m["stream"])
        elif op == "object_put":
            await core.object_put(m["bucket"], m["name"], m["data"])
        elif op == "object_get":
            return await core.object_get(m["bucket"], m["name"])
        elif op == "object_delete":
            await core.object_delete(m["bucket"], m["name"])
        else:
            raise ValueError(f"unknown op {op}")
        return None

    async def _start_watch(self, wid, prefix):
        watch = await self.core.watch_prefix(prefix)
        self._watch_handles[wid] = watch

        async def pump():
            async for ev in watch:
                await self._send({"t": "watch_ev", "wid": wid, "ev": ev.type, "key": ev.key, "value": ev.value})

        self._watch_tasks[wid] = asyncio.get_running_loop().create_task(pump())
        return watch.snapshot

    async def _stop_watch(self, wid):
        task = self._watch_tasks.pop(wid, None)
        handle = self._watch_handles.pop(wid, None)
        if handle:
            await handle.cancel()
        if task:
            task.cancel()

    async def _start_sub(self, sid, subject, queue_group):
        sub = await self.core.subscribe(subject, queue_group)
        self._sub_handles[sid] = sub

        async def pump():
            async for subj, payload in sub:
                await self._send({"t": "sub_msg", "sid": sid, "subject": subj, "payload": payload})

        self._sub_tasks[sid] = asyncio.get_running_loop().create_task(pump())

    async def _start_stream_sub(self, sid, stream, start_seq):
        sub = await self.core.stream_subscribe(stream, start_seq)
        self._sub_handles[sid] = sub

        async def pump():
            async for seq, payload in sub:
                await self._send({"t": "stream_msg", "sid": sid, "seq": seq, "payload": payload})

        self._sub_tasks[sid] = asyncio.get_running_loop().create_task(pump())

    async def _stop_sub(self, sid):
        task = self._sub_tasks.pop(sid, None)
        handle = self._sub_handles.pop(sid, None)
        if handle:
            await handle.cancel()  # type: ignore[attr-defined]
        if task:
            task.cancel()

    async def _start_serve(self, svc_id, subject):
        async def forward(payload: bytes) -> bytes:
            self._next_rid += 1
            rid = self._next_rid
            fut = asyncio.get_running_loop().create_future()
            self._pending_svc[rid] = fut
            try:
                await self._send(
                    {"t": "svc_req", "rid": rid, "svc_id": svc_id, "subject": subject, "payload": payload}
                )
                return await fut
            finally:
                # On timeout/cancellation the caller abandons the future;
                # drop the entry so it cannot accumulate for the conn lifetime.
                self._pending_svc.pop(rid, None)

        cancel = await self.core.serve(subject, forward, owner=self)
        self._svc_cancels[svc_id] = cancel


class RemoteControlPlane(ControlPlane):
    """TCP client to a :class:`ControlPlaneServer`.

    Survives hub restarts (r1 verdict weak #8: a dropped connection used to
    permanently kill the client): on connection loss the client reconnects
    with backoff and REPLAYS its registered state — service registrations,
    prefix watches (fresh snapshots delivered as synthetic puts), pub/sub
    subscriptions, and durable-stream subscriptions resumed from the last
    seen seq. In-flight request futures fail with ControlPlaneClosed (the
    callers' retry logic owns those); higher layers re-register leases via
    ``add_reconnect_callback``.

    ``address`` may be a comma-separated list (``h1:p1,h2:p2``) naming a
    primary plus warm standbys: connect and every reconnect attempt cycle
    through the list, and a hub answering ``standby`` counts as down — so
    a standby's promotion is discovered by ordinary failover. An epoch
    change after failover resets stream cursors exactly like a hub restart.
    """

    RECONNECT_BACKOFF = (0.2, 0.5, 1.0, 2.0, 5.0)

    def __init__(self, address: str):
        self._addrs = []
        for part in address.split(","):
            part = part.strip()
            if part:
                host, _, port = part.rpartition(":")
                self._addrs.append((host or "127.0.0.1", int(port)))
        if not self._addrs:
            raise ValueError(f"no control-plane address in {address!r}")
        self._addr_i = 0  # index of the address currently/last connected
        self._host, self._port = self._addrs[0]
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._wlock = asyncio.Lock()
        self._pending: dict[int, asyncio.Future] = {}
        self._next_id = 0
        self._watch_queues: dict[int, asyncio.Queue] = {}
        self._sub_queues: dict[int, asyncio.Queue] = {}
        self._handlers: dict[int, ServiceHandler] = {}
        self._rx_task: Optional[asyncio.Task] = None
        self._closed = False
        self._connected = False
        self._established = False  # ever fully connected (epoch verified)
        # replay metadata for reconnect
        self._serve_meta: dict[int, str] = {}  # svc_id -> subject
        self._watch_meta: dict[int, str] = {}  # wid -> prefix
        self._sub_meta: dict[int, tuple] = {}  # sid -> ("sub", subject, qg) | ("stream", stream, last_seq)
        self._reconnect_task: Optional[asyncio.Task] = None
        self._reconnect_cbs: list = []

    def add_reconnect_callback(self, cb) -> None:
        """``async cb()`` invoked after each successful reconnect+replay
        (runtime uses this to re-create its lease + registrations)."""
        self._reconnect_cbs.append(cb)

    async def _open(self, i: int) -> None:
        """Dial address ``i`` and verify it serves (standbys reject the
        epoch call). On failure the half-open conn is torn down so its rx
        task cannot linger."""
        host, port = self._addrs[i]
        self._reader, self._writer = await asyncio.open_connection(host, port)
        self._connected = True
        self._rx_task = asyncio.get_running_loop().create_task(self._rx_loop())
        try:
            epoch = await self._call("epoch", timeout=10.0)
        except Exception:
            self._connected = False
            try:
                self._writer.close()
            except Exception:
                pass
            raise
        self._addr_i = i
        self._host, self._port = host, port
        self._new_epoch = epoch

    async def connect(self) -> "RemoteControlPlane":
        last_err: Optional[Exception] = None
        for off in range(len(self._addrs)):
            try:
                await self._open((self._addr_i + off) % len(self._addrs))
                self._epoch = self._new_epoch
                self._established = True
                return self
            except Exception as e:  # noqa: BLE001 — try the next address
                last_err = e
        raise last_err

    async def _rx_loop(self):
        try:
            while True:
                msg = await read_frame(self._reader)
                t = msg.get("t")
                if t == "res":
                    fut = self._pending.pop(msg["id"], None)
                    if fut and not fut.done():
                        if msg["ok"]:
                            fut.set_result(msg.get("value"))
                        elif msg.get("error") == "no_responders":
                            fut.set_exception(NoRespondersError(msg.get("detail", "")))
                        else:
                            fut.set_exception(RuntimeError(msg.get("detail", "control plane error")))
                elif t == "watch_ev":
                    q = self._watch_queues.get(msg["wid"])
                    if q:
                        q.put_nowait(WatchEvent(msg["ev"], msg["key"], msg.get("value") or b""))
                elif t == "sub_msg":
                    q = self._sub_queues.get(msg["sid"])
                    if q:
                        q.put_nowait((msg["subject"], msg["payload"]))
                elif t == "stream_msg":
                    sid = msg["sid"]
                    q = self._sub_queues.get(sid)
                    if q:
                        meta = self._sub_meta.get(sid)
                        if meta and meta[0] == "stream":
                            self._sub_meta[sid] = ("stream", meta[1], msg["seq"])
                        q.put_nowait((msg["seq"], msg["payload"]))
                elif t == "svc_req":
                    asyncio.get_running_loop().create_task(self._handle_svc(msg))
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self._connected = False
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ControlPlaneClosed())
            self._pending.clear()
            if not self._closed and self._established:
                # guard against duplicate loops: a replay failure inside a
                # RUNNING reconnect loop also lands here when its fresh
                # rx task dies — that loop keeps retrying, don't stack one.
                # (_established gates out rx tasks of PROBE connections made
                # while connect() is still cycling the address list)
                if self._reconnect_task is None or self._reconnect_task.done():
                    logger.warning("control-plane connection lost; reconnecting")
                    self._reconnect_task = asyncio.get_running_loop().create_task(
                        self._reconnect_loop())
            else:
                for q in list(self._watch_queues.values()) + list(self._sub_queues.values()):
                    q.put_nowait(None)

    async def _reconnect_loop(self):
        attempt = 0
        while not self._closed:
            delay = self.RECONNECT_BACKOFF[
                min(attempt, len(self.RECONNECT_BACKOFF) - 1)]
            await asyncio.sleep(delay)
            attempt += 1
            try:
                # cycle the address list: the current hub first, then its
                # standbys — a promoted standby is found within one cycle
                await self._open((self._addr_i + attempt - 1)
                                 % len(self._addrs))
                await self._replay()
                logger.info("control-plane reconnected after %d attempt(s)",
                            attempt)
                for cb in list(self._reconnect_cbs):
                    try:
                        await cb()
                    except Exception:
                        logger.exception("reconnect callback failed")
                return
            except Exception:
                self._connected = False
                if self._writer is not None:
                    try:  # make sure a half-open conn's rx task dies
                        self._writer.close()
                    except Exception:
                        pass
                logger.warning("control-plane reconnect attempt %d failed",
                               attempt)

    async def _replay(self):
        """Re-establish serves, watches, and subscriptions on the new conn."""
        # epoch check: a RESTARTED hub resets stream seq counters, so seqs
        # from the previous epoch are meaningless — resume every stream from
        # 0 (comparing seqs alone cannot detect a restarted hub whose new
        # counter already passed our old high-water mark)
        epoch = await self._call("epoch")
        new_epoch = epoch != getattr(self, "_epoch", None)
        self._epoch = epoch
        if new_epoch:
            for sid, meta in list(self._sub_meta.items()):
                if meta[0] == "stream":
                    self._sub_meta[sid] = ("stream", meta[1], 0)
                    # A promoted standby CONTINUES the replicated seq
                    # numbering, so publishes the old primary took after the
                    # last replication tick are lost without any seq gap the
                    # consumer could observe — its next delivered seq is
                    # contiguous with the last one it saw. Surface the
                    # discontinuity in-band: a negative-seq marker ahead of
                    # the re-subscribed tail tells stream consumers (the KV
                    # indexers) to treat their state as suspect and resync
                    # instead of waiting for the audit cadence to notice.
                    q = self._sub_queues.get(sid)
                    if q is not None:
                        q.put_nowait((EPOCH_MARKER_SEQ,
                                      msgpack.packb({"epoch_changed": epoch})))
        for svc_id, subject in list(self._serve_meta.items()):
            await self._call("serve", svc_id=svc_id, subject=subject)
        for wid, prefix in list(self._watch_meta.items()):
            snapshot = await self._call("watch", wid=wid, prefix=prefix)
            q = self._watch_queues.get(wid)
            if q is not None:
                # deliver the fresh snapshot as synthetic puts — watch
                # consumers (discovery, clients) apply puts idempotently;
                # deletions during the outage surface as NoResponders later
                for k, v in (snapshot or {}).items():
                    q.put_nowait(WatchEvent("put", k, v or b""))
        for sid, meta in list(self._sub_meta.items()):
            if meta[0] == "sub":
                await self._call("subscribe", sid=sid, subject=meta[1],
                                 queue_group=meta[2])
            else:
                await self._call("stream_subscribe", sid=sid, stream=meta[1],
                                 start_seq=meta[2])

    async def _handle_svc(self, msg):
        handler = self._handlers.get(msg["svc_id"])
        if handler is None:
            await self._send({"t": "svc_res", "rid": msg["rid"], "ok": False, "error": "no handler"})
            return
        try:
            result = await handler(msg["payload"])
            await self._send({"t": "svc_res", "rid": msg["rid"], "ok": True, "payload": result})
        except Exception as e:
            logger.exception("service handler failed")
            await self._send({"t": "svc_res", "rid": msg["rid"], "ok": False, "error": repr(e)})

    async def _send(self, obj):
        if self._closed or not self._connected:
            raise ControlPlaneClosed()
        async with self._wlock:
            await write_frame(self._writer, obj)

    async def _call(self, op: str, timeout: float = 60.0, **kwargs):
        self._next_id += 1
        rid = self._next_id
        fut = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        try:
            await self._send({"t": "req", "id": rid, "op": op, **kwargs})
            return await asyncio.wait_for(fut, timeout)
        finally:
            # a send failure/timeout abandons the future — drop it so a
            # later rx-loop teardown can't set an exception nobody will
            # ever retrieve (the loop's exception handler would flag it)
            self._pending.pop(rid, None)
            if fut.done() and not fut.cancelled():
                fut.exception()  # mark retrieved (timeout/send-fail races)
            else:
                fut.cancel()

    # -- KV --
    async def kv_put(self, key, value, lease_id=None):
        await self._call("kv_put", key=key, value=value, lease=lease_id)

    async def kv_create(self, key, value, lease_id=None) -> bool:
        return await self._call("kv_create", key=key, value=value, lease=lease_id)

    async def kv_get(self, key):
        return await self._call("kv_get", key=key)

    async def kv_get_prefix(self, prefix):
        return await self._call("kv_get_prefix", prefix=prefix)

    async def kv_delete(self, key):
        return await self._call("kv_delete", key=key)

    async def kv_delete_prefix(self, prefix):
        return await self._call("kv_delete_prefix", prefix=prefix)

    async def watch_prefix(self, prefix) -> Watch:
        self._next_id += 1
        wid = self._next_id
        q: asyncio.Queue = asyncio.Queue()
        self._watch_queues[wid] = q
        self._watch_meta[wid] = prefix
        snapshot = await self._call("watch", wid=wid, prefix=prefix)

        async def cancel():
            self._watch_queues.pop(wid, None)
            self._watch_meta.pop(wid, None)
            q.put_nowait(None)
            if not self._closed:
                try:
                    await self._call("watch_cancel", wid=wid)
                except ControlPlaneClosed:
                    pass

        return Watch(dict(snapshot or {}), q, cancel)

    # -- Leases --
    async def lease_create(self, ttl=DEFAULT_LEASE_TTL) -> int:
        return await self._call("lease_create", ttl=ttl)

    async def lease_keepalive(self, lease_id) -> bool:
        return await self._call("lease_keepalive", lease=lease_id)

    async def lease_revoke(self, lease_id):
        await self._call("lease_revoke", lease=lease_id)

    # -- Pub/sub --
    async def publish(self, subject, payload):
        chaos = get_chaos()
        if chaos is not None:
            await chaos.pre("plane.publish")
            if chaos.should_drop("plane.publish"):
                return  # injected loss before the hub ever sees the message
        await self._call("publish", subject=subject, payload=payload)

    async def subscribe(self, subject, queue_group=None) -> Subscription:
        self._next_id += 1
        sid = self._next_id
        q: asyncio.Queue = asyncio.Queue()
        self._sub_queues[sid] = q
        self._sub_meta[sid] = ("sub", subject, queue_group)
        await self._call("subscribe", sid=sid, subject=subject, queue_group=queue_group)

        async def cancel():
            self._sub_queues.pop(sid, None)
            self._sub_meta.pop(sid, None)
            q.put_nowait(None)
            if not self._closed:
                try:
                    await self._call("sub_cancel", sid=sid)
                except ControlPlaneClosed:
                    pass

        return Subscription(q, cancel)

    async def request(self, subject, payload, timeout=30.0) -> bytes:
        return await self._call(
            "request", timeout=timeout + 5.0, subject=subject, payload=payload, req_timeout=timeout
        )

    async def serve(self, subject, handler):
        self._next_id += 1
        svc_id = self._next_id
        self._handlers[svc_id] = handler
        self._serve_meta[svc_id] = subject
        await self._call("serve", svc_id=svc_id, subject=subject)

        async def cancel():
            self._handlers.pop(svc_id, None)
            self._serve_meta.pop(svc_id, None)
            if not self._closed:
                try:
                    await self._call("serve_cancel", svc_id=svc_id)
                except ControlPlaneClosed:
                    pass

        return cancel

    # -- Work queues --
    async def queue_push(self, queue, payload):
        await self._call("queue_push", queue=queue, payload=payload)

    async def queue_pop(self, queue, timeout: float = 30.0):
        return await self._call("queue_pop", timeout=timeout + 5.0,
                                queue=queue, pop_timeout=timeout)

    async def queue_depth(self, queue) -> int:
        return await self._call("queue_depth", queue=queue)

    # -- Streams --
    async def stream_publish(self, stream, payload) -> int:
        return await self._call("stream_publish", stream=stream, payload=payload)

    async def stream_subscribe(self, stream, start_seq=0) -> StreamSub:
        self._next_id += 1
        sid = self._next_id
        q: asyncio.Queue = asyncio.Queue()
        self._sub_queues[sid] = q
        self._sub_meta[sid] = ("stream", stream, start_seq)
        await self._call("stream_subscribe", sid=sid, stream=stream, start_seq=start_seq)

        async def cancel():
            self._sub_queues.pop(sid, None)
            self._sub_meta.pop(sid, None)
            q.put_nowait(None)
            if not self._closed:
                try:
                    await self._call("sub_cancel", sid=sid)
                except ControlPlaneClosed:
                    pass

        return StreamSub(q, cancel)

    async def stream_last_seq(self, stream) -> int:
        return await self._call("stream_last_seq", stream=stream)

    async def stream_first_seq(self, stream) -> int:
        return await self._call("stream_first_seq", stream=stream)

    async def get_epoch(self) -> str:
        return await self._call("epoch")

    async def hub_stats(self) -> dict:
        """The hub's self-instrumentation (event counters + publish
        latency) — surfaced by ``dynctl top`` and the metrics aggregator."""
        return await self._call("hub_stats")

    # -- Object store --
    async def object_put(self, bucket, name, data):
        await self._call("object_put", bucket=bucket, name=name, data=data)

    async def object_get(self, bucket, name):
        return await self._call("object_get", bucket=bucket, name=name)

    async def object_delete(self, bucket, name):
        await self._call("object_delete", bucket=bucket, name=name)

    async def close(self):
        self._closed = True
        if self._reconnect_task:
            self._reconnect_task.cancel()
        if self._rx_task:
            self._rx_task.cancel()
        if self._writer:
            try:
                self._writer.close()
            except Exception:
                pass
        for q in list(self._watch_queues.values()) + list(self._sub_queues.values()):
            q.put_nowait(None)
