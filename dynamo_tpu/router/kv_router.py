"""KvRouter: the composed KV-aware routing engine.

Rebuild of the reference's ``KvRouter``/``KvPushRouter`` (ref: lib/llm/src/
kv_router.rs:210-435,473-612): composes the radix indexer (event-fed or
approximate) with the cost scheduler, exposes ``find_best_match`` plus the
request lifecycle (add → mark_prefill_completed → free), and wraps an endpoint
Client as an engine operator that:

- honors ``backend_instance_id`` pins (direct route),
- answers ``query_instance_id`` annotations with a dry route (no generation),
- sets ``estimated_prefix_hit_num_blocks`` on the outgoing request,
- marks prefill complete on the first output, frees on stream end,
- reports dead instances to discovery and evicts them from the radix tree.
"""

from __future__ import annotations

import asyncio
import logging
from typing import AsyncIterator, Optional

from dynamo_tpu.protocols import Annotated, PreprocessedRequest
from dynamo_tpu.router.indexer import ApproxKvIndexer, KvIndexer, OverlapScores
from dynamo_tpu.router.protocols import G4_SOURCE_ID, KvRouterConfig
from dynamo_tpu.router.scheduler import KvScheduler, NoWorkersError, SchedulingDecision
from dynamo_tpu.runtime.component import Client
from dynamo_tpu.runtime.context import (
    Context,
    DeadlineExceededError,
    StreamError,
)
from dynamo_tpu.runtime.control_plane import NoRespondersError
from dynamo_tpu.tokens import compute_block_hash_for_seq, compute_seq_hash_for_block

logger = logging.getLogger("dynamo.kv_router")


#: pub/sub subject for cross-replica routing-decision sync
#: (ref: subjects prefill_events / active_sequences_events, kv_router.rs:64-65)
ROUTER_SYNC_SUBJECT = "router_sync"


class KvRouter:
    def __init__(self, plane, block_size: int, config: Optional[KvRouterConfig] = None):
        import uuid

        self.plane = plane
        self.block_size = block_size
        self.config = config or KvRouterConfig()
        if self.config.use_kv_events:
            self.indexer: KvIndexer | ApproxKvIndexer = KvIndexer(
                plane, block_size,
                snapshot_threshold=self.config.router_snapshot_threshold,
                reset_states=self.config.router_reset_states)
        else:
            self.indexer = ApproxKvIndexer(block_size)
        self.scheduler = KvScheduler(block_size, self.config)
        #: identifies this replica in sync messages (skip own echoes)
        self.replica_id = uuid.uuid4().hex
        self._sync_sub = None
        self._sync_task = None
        self._publish_tasks: set = set()  # strong refs: loop holds only weak
        #: KV index audit plane (docs/observability.md "KV audit"):
        #: started with the event-fed indexer unless DYN_KV_AUDIT=0 —
        #: the approx indexer predicts contents by construction, so
        #: there is no truth claim to audit there
        self.auditor = None

    async def start(self) -> "KvRouter":
        if isinstance(self.indexer, KvIndexer):
            await self.indexer.start()
            from dynamo_tpu.observability.kvaudit import (AuditConfig,
                                                          KvAuditor)
            acfg = AuditConfig.from_env()
            if acfg.enabled:
                self.auditor = await KvAuditor(
                    self.plane, self.indexer, acfg).start()
        if self.config.router_replica_sync:
            self._sync_sub = await self.plane.subscribe(ROUTER_SYNC_SUBJECT)
            self._sync_task = asyncio.get_running_loop().create_task(
                self._sync_loop())
        return self

    async def stop(self):
        if self.auditor is not None:
            await self.auditor.stop()
        if isinstance(self.indexer, KvIndexer):
            await self.indexer.stop()
        if self._sync_task:
            self._sync_task.cancel()
        if self._sync_sub:
            await self._sync_sub.cancel()

    # -- replica sync (ref: sequence.rs:283-340) ----------------------------

    def _publish_sync(self, op: str, request_id: str, **extra) -> None:
        """Fire-and-forget broadcast of a local routing decision so OTHER
        router replicas account this load in their ActiveSequences."""
        import msgpack

        msg = {"origin": self.replica_id, "op": op,
               "request_id": request_id, **extra}
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # sync caller outside an event loop (unit tests)
        task = loop.create_task(self.plane.publish(
            ROUTER_SYNC_SUBJECT, msgpack.packb(msg)))
        self._publish_tasks.add(task)

        def done(t):
            self._publish_tasks.discard(t)
            if not t.cancelled() and t.exception() is not None:
                logger.warning("router sync publish failed: %r", t.exception())

        task.add_done_callback(done)

    async def _sync_loop(self):
        import msgpack

        try:
            async for _subject, payload in self._sync_sub:
                try:
                    m = msgpack.unpackb(payload, raw=False)
                    if m.get("origin") == self.replica_id:
                        continue
                    op, rid = m["op"], m["request_id"]
                    if op == "add":
                        self.scheduler.slots.add_request(
                            rid, m["worker_id"], m.get("seq_hashes"),
                            m["isl_tokens"], m["overlap"])
                    elif op == "prefill_done":
                        self.scheduler.mark_prefill_completed(rid)
                    elif op == "free":
                        self.scheduler.free(rid)
                except Exception:
                    logger.exception("bad router sync message ignored")
        except asyncio.CancelledError:
            pass

    def find_best_match(
        self,
        request_id: str,
        token_ids: list[int],
        worker_ids: list[int],
        router_config_override: Optional[dict] = None,
        priority: Optional[str] = None,
        link_costs: Optional[dict[int, float]] = None,
        affinity_worker: Optional[int] = None,
    ) -> SchedulingDecision:
        local = compute_block_hash_for_seq(token_ids, self.block_size)
        seq_hashes = compute_seq_hash_for_block(local)
        overlaps = self.indexer.find_matches(local)
        # a dead affinity worker must not attract a session to a corpse —
        # the bonus only applies to a live candidate
        if affinity_worker is not None and affinity_worker not in worker_ids:
            affinity_worker = None
        decision = self.scheduler.schedule(
            request_id,
            isl_tokens=len(token_ids),
            seq_hashes=seq_hashes,
            overlaps=overlaps,
            worker_ids=worker_ids,
            router_config_override=router_config_override,
            priority=priority,
            link_costs=link_costs,
            affinity_worker=affinity_worker,
        )
        decision.best_overlap_blocks = overlaps.best()
        if isinstance(self.indexer, ApproxKvIndexer):
            self.indexer.process_routing_decision_for_request(token_ids, decision.worker_id)
        if self.config.router_replica_sync:
            track = (seq_hashes
                     if self.config.router_track_active_blocks else None)
            self._publish_sync(
                "add", request_id, worker_id=decision.worker_id,
                isl_tokens=len(token_ids), overlap=decision.overlap_blocks,
                seq_hashes=track)
        return decision

    def request_resync(self) -> None:
        """Ask every worker to re-announce its cache contents (idempotent
        upserts). Used after a re-registration purge: the discovery watch
        and the KV event stream are unordered relative to each other, so
        the purge may have wiped events the worker's NEW life already
        published — the replay restores them."""
        if not isinstance(self.indexer, KvIndexer):
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # sync caller outside an event loop (unit tests)
        task = loop.create_task(self.indexer._request_resync())
        self._publish_tasks.add(task)
        task.add_done_callback(self._publish_tasks.discard)

    def restore_sources(self, token_ids: list[int]) -> dict[int, int]:
        """KV-restore query (docs/robustness.md): per-worker contiguous
        prefix length (blocks) of ``token_ids`` resident anywhere in the
        fleet, per the radix index. Dead workers are absent — lease expiry
        purges them from the tree before Migration re-dispatches."""
        local = compute_block_hash_for_seq(token_ids, self.block_size)
        return self.indexer.prefix_sources(local)

    def mark_prefill_completed(self, request_id: str):
        self.scheduler.mark_prefill_completed(request_id)
        if self.config.router_replica_sync:
            self._publish_sync("prefill_done", request_id)

    def free(self, request_id: str):
        self.scheduler.free(request_id)
        if self.config.router_replica_sync:
            self._publish_sync("free", request_id)

    def remove_worker(self, worker_id: int):
        self.indexer.remove_worker(worker_id)


class KvPushRouter:
    """Engine operator: route a PreprocessedRequest to the best worker.

    ``prefill_client`` (optional) watches the prefill component's
    instances; when that pool publishes locality labels the routing logit
    gains a topology-costed KV-transfer term (router/topology.py) so the
    decode choice accounts for where the prefill fleet's KV bytes must
    travel. Without the client — or with an unlabeled fleet — routing is
    exactly the topology-blind cost function.
    """

    #: restore plans carry at most this many ranked sources — the worker
    #: tries the best and fails over once; a longer list is dead weight
    RESTORE_PLAN_SOURCES = 4

    def __init__(self, client: Client, router: KvRouter,
                 prefill_client: Optional[Client] = None):
        self.client = client
        self.router = router
        self.prefill_client = prefill_client
        self._topo_model = None
        # memoized (key, costs): the sources×workers sweep only changes
        # when an instance (de)registers, not per routed request
        self._link_cache: Optional[tuple] = None
        # memoized worker↔worker link costs for restore-plan ranking;
        # purged (with the radix tree) on lease expiry/deregistration
        self._peer_cache: Optional[tuple] = None
        #: instance ids seen deregistering — a later re-registration of
        #: the SAME id must not resurrect its previous life's KV index
        #: entries (dead-instance hygiene, docs/robustness.md)
        self._dead_ids: set[int] = set()
        #: routine prefix onboarding (docs/performance.md): DYN_ONBOARD=0
        #: is the one-switch escape to pre-onboard behavior at both ends
        #: (no plan on the wire here, no pull at the worker)
        import os as _os

        self._onboard_on = (self.router.config.onboard_enabled
                            and _os.environ.get("DYN_ONBOARD", "1")
                            not in ("0", "false", "off"))
        add = getattr(client, "add_instance_listener", None)
        if add is not None:
            add(self._on_instance_event)

    def _on_instance_event(self, typ: str, instance_id: int) -> None:
        """Discovery watch events: proactive death handling. On delete
        (lease expiry / deregistration) the worker's blocks leave the
        radix tree and the memoized link-cost matrices IMMEDIATELY — a
        restore plan must never point a pull at a corpse, and Migration
        re-dispatches the victim's streams the moment the lease lapses."""
        if typ == "delete":
            self._dead_ids.add(instance_id)
            self.router.remove_worker(instance_id)
            self._link_cache = None
            self._peer_cache = None
        elif instance_id in self._dead_ids:
            # re-registered id: purge whatever its previous life left in
            # the tree BEFORE the new life's events repopulate it. The
            # watch and the event stream are unordered, so the purge may
            # also catch events the new life already published — ask for
            # a replay (idempotent upserts) to restore those.
            self._dead_ids.discard(instance_id)
            self.router.remove_worker(instance_id)
            self._link_cache = None
            self._peer_cache = None
            self.router.request_resync()

    def _link_costs(self) -> Optional[dict[int, float]]:
        """Per-decode-worker relative KV-transfer cost from the prefill
        pool, or None (topology-blind) when disabled or unlabeled."""
        cfg = self.router.config
        if self.prefill_client is None or cfg.transfer_cost_weight <= 0:
            return None
        from dynamo_tpu.router.topology import (
            TopologyCostModel, TopologyLabels, link_costs,
        )

        pre_insts = self.prefill_client.instances()
        wk_insts = self.client.instances()
        # Instance objects are rebuilt per registration event, so object
        # identity is a change detector for membership AND metadata
        key = (tuple(map(id, pre_insts)), tuple(map(id, wk_insts)))
        if self._link_cache is not None and self._link_cache[0] == key:
            return self._link_cache[1]
        sources = [TopologyLabels.from_metadata(i.metadata)
                   for i in pre_insts]
        if not any(sources):
            costs = None
        else:
            if self._topo_model is None:
                self._topo_model = TopologyCostModel(cfg.link_gbps)
            workers = {i.instance_id: TopologyLabels.from_metadata(i.metadata)
                       for i in wk_insts}
            costs = link_costs(sources, workers, self._topo_model)
        self._link_cache = (key, costs)
        return costs

    def _peer_costs(self) -> dict[int, "object"]:
        """Memoized worker-id → TopologyLabels map for restore-plan source
        ranking (worker↔worker, unlike _link_costs' prefill→worker sweep).
        Instance identity is the change detector, same as _link_costs."""
        from dynamo_tpu.router.topology import TopologyLabels

        insts = self.client.instances()
        key = tuple(map(id, insts))
        if self._peer_cache is not None and self._peer_cache[0] == key:
            return self._peer_cache[1]
        labels = {i.instance_id: TopologyLabels.from_metadata(i.metadata)
                  for i in insts}
        self._peer_cache = (key, labels)
        return labels

    def _restore_plan(self, req: PreprocessedRequest, worker_id: int) -> None:
        """Extend a migrated request's restore hint with ranked pull
        sources: the longest recoverable prefix first, topology-cheapest
        link breaking ties (NetKV-style source selection). The chosen
        worker itself is excluded — whatever it holds is a local prefix
        hit, not a pull."""
        from dynamo_tpu.router.topology import (
            TopologyCostModel, TopologyLabels, link_class,
        )

        sources = self.router.restore_sources(req.token_ids)
        sources.pop(worker_id, None)
        # the G4 sentinel is not a pullable instance — a restore plan slot
        # spent on it would burn one of the worker's two pull attempts
        sources.pop(G4_SOURCE_ID, None)
        if not sources:
            req.restore = {**req.restore,
                           "block_size": self.router.block_size,
                           "sources": []}
            return
        labels = self._peer_costs()
        if self._topo_model is None:
            self._topo_model = TopologyCostModel(self.router.config.link_gbps)
        dst = labels.get(worker_id) or TopologyLabels()
        empty = TopologyLabels()
        ranked = sorted(
            ((wid, blocks,
              self._topo_model.rel_cost(link_class(
                  labels.get(wid) or empty, dst)))
             for wid, blocks in sources.items()),
            key=lambda t: (-t[1], t[2], t[0]))
        req.restore = {
            **req.restore,
            "block_size": self.router.block_size,
            "sources": [[wid, blocks, cost] for wid, blocks, cost
                        in ranked[:self.RESTORE_PLAN_SOURCES]],
        }

    def _onboard_plan(self, req: PreprocessedRequest, decision) -> bool:
        """Routine prefix onboarding (docs/performance.md): when peers (or
        the G4 object store) hold more of this prompt's prefix than the
        chosen worker, and pulling the missing blocks is cheaper than
        recomputing them under the admission cost model, attach a ranked
        pull plan — same shape as a restore plan, same worker-side
        machinery. Returns True when a plan was attached."""
        from dynamo_tpu.router.topology import (
            TopologyCostModel, TopologyLabels, link_class,
        )

        cfg = self.router.config
        bs = self.router.block_size
        overlap = decision.overlap_blocks
        # a worker attaches at most the prompt's full blocks minus one
        # token (engine.restore_probe) — clamp every source to that
        matchable = (len(req.token_ids) - 1) // bs
        if matchable <= 0:
            return False
        # cheap gate: find_matches already told us the fleet's deepest
        # overlap; only a meaningful gap is worth the prefix_sources walk
        if (min(decision.best_overlap_blocks, matchable) - overlap
                < cfg.onboard_min_blocks):
            return False
        sources = self.router.restore_sources(req.token_ids)
        g4_blocks = min(sources.pop(G4_SOURCE_ID, 0), matchable)
        sources.pop(decision.worker_id, None)
        labels = self._peer_costs()
        if self._topo_model is None:
            self._topo_model = TopologyCostModel(cfg.link_gbps)
        dst = labels.get(decision.worker_id) or TopologyLabels()
        empty = TopologyLabels()
        recompute_ms_per_block = bs * cfg.onboard_recompute_ms_per_token
        ranked = []
        for wid, blocks in sources.items():
            gain = min(blocks, matchable) - overlap
            if gain < cfg.onboard_min_blocks:
                continue
            rel = self._topo_model.rel_cost(link_class(
                labels.get(wid) or empty, dst))
            # the admission decision: pull only where it beats recompute
            if cfg.onboard_pull_ms_per_block * rel < recompute_ms_per_block:
                ranked.append((wid, min(blocks, matchable), rel))
        g4_wins = (g4_blocks - overlap >= cfg.onboard_min_blocks
                   and cfg.onboard_g4_ms_per_block < recompute_ms_per_block)
        if not ranked and not g4_wins:
            return False
        ranked.sort(key=lambda t: (-t[1], t[2], t[0]))
        plan = {
            "block_size": bs,
            "sources": [[wid, blocks, cost] for wid, blocks, cost
                        in ranked[:self.RESTORE_PLAN_SOURCES]],
        }
        if g4_wins:
            plan["g4_blocks"] = g4_blocks
        req.onboard = plan
        return True

    async def generate(self, req: PreprocessedRequest, ctx: Context) -> AsyncIterator:
        if isinstance(req, dict):
            req = PreprocessedRequest.from_wire(req)

        if ctx.expired:
            # refuse to spend routing/scheduler state on dead work — the
            # expired request must never reach a worker
            raise DeadlineExceededError(
                "request deadline expired before routing")

        if req.backend_instance_id is not None:
            async for item in self._stream_to(req, ctx, req.backend_instance_id, None):
                yield item
            return

        from dynamo_tpu.observability import get_tracer

        with get_tracer().span("router.schedule", ctx,
                               service="router") as sp:
            worker_ids = self.client.available_ids()
            if not worker_ids:
                try:
                    worker_ids = await self.client.wait_for_instances(
                        timeout=5.0)
                except TimeoutError as e:
                    # fleet blackout (every worker dead at once, e.g. a
                    # correlated kill): a bare TimeoutError escapes both
                    # Migration and the frontend's typed handlers and
                    # truncates the client stream as a generic 500. Type it
                    # so Migration can re-send once the operator restarts
                    # workers, and the frontend maps exhaustion to a 503.
                    raise NoRespondersError(str(e)) from e
            try:
                # class-biased cost (docs/qos.md): interactive requests
                # avoid saturated workers, batch chases cache overlap;
                # returning sessions pull softly toward their affinity
                # worker (docs/sessions.md)
                decision = self.router.find_best_match(
                    ctx.id, req.token_ids, worker_ids,
                    req.router_config_override,
                    priority=getattr(ctx, "priority", None),
                    link_costs=self._link_costs(),
                    affinity_worker=getattr(ctx, "session_affinity", None),
                )
            except NoWorkersError as e:
                raise NoRespondersError(str(e)) from e
            sp.set(worker_id=f"{decision.worker_id:x}",
                   overlap_blocks=decision.overlap_blocks,
                   candidates=len(worker_ids),
                   tenant=getattr(ctx, "tenant", None) or "default",
                   qos=getattr(ctx, "priority", None) or "standard",
                   session=getattr(ctx, "session", None) or "")
            # session feedback (docs/sessions.md): the frontend registry
            # runs in this same process — hand it the serving worker and
            # the exact prompt token ids (the hash chain a later park must
            # address) at decision time, before the stream even starts
            on_routed = getattr(ctx, "on_routed", None)
            if on_routed is not None:
                try:
                    on_routed(decision.worker_id, req.token_ids)
                except Exception:
                    logger.exception("session on_routed hook failed")
            ctx.routed_worker = decision.worker_id

        if req.has_annotation("query_instance_id"):
            # dry route: report the decision without generating
            self.router.free(ctx.id)
            yield Annotated(
                event="worker_instance_id",
                data={"worker_id": decision.worker_id, "overlap_blocks": decision.overlap_blocks},
                id=ctx.id,
            ).to_wire()
            return

        req.estimated_prefix_hit_num_blocks = decision.overlap_blocks
        if (self._onboard_on and req.restore is None
                and req.onboard is None):
            # routine onboarding: the fleet's hot prefixes are a pull
            # away — attach the plan when the cost model says pull wins
            if self._onboard_plan(req, decision):
                with get_tracer().span("router.onboard_plan", ctx,
                                       service="router") as osp:
                    osp.set(sources=len(req.onboard.get("sources") or []),
                            g4_blocks=req.onboard.get("g4_blocks", 0),
                            best_blocks=max(
                                (s[1] for s in req.onboard["sources"]),
                                default=req.onboard.get("g4_blocks", 0)))
        if req.restore is not None and "sources" not in req.restore:
            # migrated request: attach the KV-restore plan for the chosen
            # worker (docs/robustness.md) so it can pull the recoverable
            # prefix from surviving peers instead of re-prefilling
            with get_tracer().span("router.restore_plan", ctx,
                                   service="router") as rsp:
                self._restore_plan(req, decision.worker_id)
                rsp.set(sources=len(req.restore.get("sources") or []),
                        best_blocks=max(
                            (s[1] for s in req.restore["sources"]),
                            default=0))
        async for item in self._stream_to(req, ctx, decision.worker_id, decision):
            yield item

    async def _stream_to(
        self,
        req: PreprocessedRequest,
        ctx: Context,
        instance_id: int,
        decision: Optional[SchedulingDecision],
    ) -> AsyncIterator:
        tracked = decision is not None
        prefill_done = False
        try:
            stream = await self.client.generate(
                req.to_wire(), ctx=ctx, mode="direct", instance_id=instance_id
            )
        except (NoRespondersError, StreamError) as e:
            if tracked:
                self.router.free(ctx.id)
            if isinstance(e, StreamError) and not e.retryable:
                # typed TERMINAL rejection (overloaded/deadline): the worker
                # is healthy and shed on purpose — evicting it from routing
                # or laundering the error into a retryable StreamError would
                # defeat the taxonomy (Migration would re-send to a
                # saturated fleet and the fleet would bleed workers)
                raise
            self.client.report_instance_down(instance_id)
            self.router.remove_worker(instance_id)
            raise StreamError(f"worker {instance_id:x} unavailable: {e}") from e
        try:
            async for item in stream:
                if tracked and not prefill_done:
                    self.router.mark_prefill_completed(ctx.id)
                    prefill_done = True
                yield item
        except StreamError as e:
            if e.retryable:  # same rule mid-stream: terminal ≠ worker death
                self.client.report_instance_down(instance_id)
                self.router.remove_worker(instance_id)
            raise
        finally:
            if tracked:
                self.router.free(ctx.id)
