"""Per-worker step flight recorder: what every engine step DID, and why it
was slow, in a bounded ring the whole fleet can be asked about.

Request spans (tracing.py) answer "where did THIS request spend its time";
they cannot say that a stall was a preempt-to-swap storm, a mid-traffic XLA
compile, a budget-starved decode batch, or an empty-step memory bubble —
the *step-level* causes the flagship drive (ROADMAP item 3) has to debug.
This module is that missing layer (ref motivation: the KV-cache-management
survey's per-tier visibility argument, arXiv 2607.02574 §6):

- ``StepRecord`` — one scheduler plan / engine step: durations, decode
  rows, prefill chunks + tokens, padded tokens, compile info, preemption /
  swap deltas, queue depths, KV tier occupancy G1–G4, onboard/restore
  pulls in flight, QoS class mix, and the anomaly ``tags`` computed the
  moment the record lands.
- ``FlightRecorder`` — bounded ring of records + rolling step-time
  baseline; tags are computed inline (no offline pass needed):
  ``slow-step`` (wall > kσ over the rolling baseline), ``compile`` /
  ``compile-steady`` (a fresh jit trace; -steady once past the warmup
  step count), ``preempt-storm`` (rolling preemption burst),
  ``budget-starved`` (ready decode rows left out of the step), and
  ``empty-step`` (work exists but nothing could run — a memory bubble).
- ``serve_flight`` / ``fetch_fleet_steps`` — the ``serve_traces``-style
  control-plane fan-out behind ``GET /v1/fleet/steps``, ``dynctl top``
  and ``dynctl timeline``.

Env knobs (all optional):

- ``DYN_FLIGHT=0``            — disable recording entirely (bench A/B arm)
- ``DYN_FLIGHT_CAPACITY``     — ring size in records (default 4096)
- ``DYN_FLIGHT_SIGMA``        — slow-step threshold in rolling σ (default 4)
- ``DYN_FLIGHT_STEADY_STEPS`` — steps after which a compile counts as
  steady-state (default 64)
- ``DYN_FLIGHT_STORM``        — preemptions within the rolling storm
  window (32 records) that tag a preempt-storm (default 4)
- ``DYN_STEP_JSONL=<path>``   — append every record as one JSON line
  (offline analysis; a broken sink disables itself, like DYN_TRACE_JSONL)
"""

from __future__ import annotations

import asyncio
import collections
import json
import logging
import math
import os
import secrets
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Optional

import msgpack

logger = logging.getLogger("dynamo.observability.flight")

#: process-unique recorder-instance id. Spans stamp it (engine.ttft /
#: engine.decode ``flight_instance`` attributes) and summaries carry it, so
#: the attribution join (attribution.py) can match "the worker that served
#: this request" to "that worker's step ring" without knowing lease ids —
#: several workers in one fleet share the recorder NAME ("engine"), never
#: the instance.
_INSTANCE_ID = secrets.token_hex(6)


def flight_instance() -> str:
    """This process's recorder-instance id (stable for the process life)."""
    return _INSTANCE_ID

#: discovery prefix: observability/flight/<lease-hex> → {subject, service}
FLIGHT_PREFIX = "observability/flight/"

# anomaly tag names (docs/observability.md "Flight recorder")
TAG_SLOW = "slow-step"
TAG_COMPILE = "compile"
TAG_COMPILE_STEADY = "compile-steady"
TAG_PREEMPT_STORM = "preempt-storm"
TAG_STARVED = "budget-starved"
TAG_EMPTY = "empty-step"

#: rolling windows (records, not seconds): baseline for slow-step σ and
#: the preemption burst window for preempt-storm
BASELINE_WINDOW = 256
STORM_WINDOW = 32
#: minimum baseline samples before slow-step can fire (σ of 3 samples is
#: noise) and the floor added to the σ threshold so microsecond mock steps
#: don't tag on scheduler jitter
BASELINE_MIN_SAMPLES = 16
SLOW_FLOOR_MS = 0.5


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("ignoring malformed %s=%r", name, raw)
        return default


def _env_int(name: str, default: int) -> int:
    return int(_env_float(name, float(default)))


def flight_enabled() -> bool:
    """Global recording gate (``DYN_FLIGHT=0`` = off; the bench A/B arm)."""
    return os.environ.get("DYN_FLIGHT", "1").lower() not in (
        "0", "false", "off", "no")


@dataclass
class StepRecord:
    """One engine step (or one empty-step bubble). All counts are THIS
    step's work/deltas, not cumulative totals — the ring is a timeline."""

    seq: int = 0            # monotonic step index within this recorder
    t: float = 0.0          # epoch seconds at record time
    kind: str = ""          # ragged|spec|multi|decode_pipe|mock|empty —
    #                         ONE record per plan (the packed ragged launch
    #                         is the only step path; no per-bucket records)
    wall_ms: float = 0.0    # plan+execute wall clock
    dispatch_ms: float = 0.0  # jitted-call dispatch portion (0 = unknown)
    decode_rows: int = 0
    prefill_chunks: int = 0
    chunk_tokens: int = 0   # real prefill tokens this step
    padded_tokens: int = 0  # dispatched beyond real work (bucket tails)
    compile_s: float = 0.0  # >0: this step traced a NEW jit signature
    compile_sig: str = ""   # the offending signature, printable
    preempt_swap: int = 0
    preempt_recompute: int = 0
    swap_out_blocks: int = 0
    swap_in_blocks: int = 0
    waiting: int = 0
    swapped: int = 0
    running: int = 0
    starved_decode: int = 0  # ready decode rows the step could not carry
    #: rows this step sampled under a structured-decoding constraint
    #: (device FSM or host oracle) — docs/structured.md
    constrained_rows: int = 0
    kv_tiers: dict = field(default_factory=dict)  # {g1..g4: blocks}
    onboard_inflight: int = 0
    restore_inflight: int = 0
    qos_mix: dict = field(default_factory=dict)   # {class: rows this step}
    tags: list = field(default_factory=list)
    #: step↔request linkage (attribution.py): request ids whose decode
    #: rows / prefill chunks this step carried, and the ready decode rows
    #: the token budget left out. Sparse on the wire (absent when empty) —
    #: most deployments never fetch them; the attribution join is what
    #: turns "step 4812 was slow" into "THIS request stalled 3 ms there".
    decode_ids: list = field(default_factory=list)
    prefill_ids: list = field(default_factory=list)
    starved_ids: list = field(default_factory=list)
    #: anomaly-triggered device-trace artifact (observability/profiler.py
    #: AnomalyProfiler): set on the record whose tags armed the capture,
    #: AFTER it landed in the ring (snapshots serialize lazily, so fleet
    #: queries see it; a DYN_STEP_JSONL line written at record time does
    #: not — the path is logged as well)
    profile_path: str = ""

    @property
    def tokens(self) -> int:
        return self.decode_rows + self.chunk_tokens

    def to_dict(self) -> dict:
        d = {
            "seq": self.seq, "t": self.t, "kind": self.kind,
            "wall_ms": round(self.wall_ms, 3),
            "decode_rows": self.decode_rows,
            "prefill_chunks": self.prefill_chunks,
            "chunk_tokens": self.chunk_tokens,
            "padded_tokens": self.padded_tokens,
            "waiting": self.waiting, "swapped": self.swapped,
            "running": self.running, "tags": list(self.tags),
        }
        # sparse optional fields: absent-when-zero keeps the wire/JSONL
        # compact at fleet scale (most steps are unremarkable)
        if self.dispatch_ms:
            d["dispatch_ms"] = round(self.dispatch_ms, 3)
        if self.compile_s:
            d["compile_s"] = round(self.compile_s, 4)
            d["compile_sig"] = self.compile_sig
        for k in ("preempt_swap", "preempt_recompute", "swap_out_blocks",
                  "swap_in_blocks", "starved_decode", "onboard_inflight",
                  "restore_inflight", "constrained_rows", "profile_path"):
            v = getattr(self, k)
            if v:
                d[k] = v
        for k in ("decode_ids", "prefill_ids", "starved_ids"):
            v = getattr(self, k)
            if v:
                d[k] = list(v)
        if self.kv_tiers:
            d["kv_tiers"] = dict(self.kv_tiers)
        if self.qos_mix:
            d["qos_mix"] = dict(self.qos_mix)
        return d

    @staticmethod
    def from_dict(d: dict) -> "StepRecord":
        rec = StepRecord()
        for k, v in d.items():
            if hasattr(rec, k) and k != "tokens":
                setattr(rec, k, v)
        rec.tags = list(d.get("tags") or [])
        rec.kv_tiers = dict(d.get("kv_tiers") or {})
        rec.qos_mix = dict(d.get("qos_mix") or {})
        return rec


class FlightRecorder:
    """Bounded step-record ring + inline anomaly tagging.

    Thread-safe: engine loops record from the event loop while scrapes /
    fan-out queries snapshot from other tasks (and the offload thread may
    bump the inflight gauges).
    """

    def __init__(self, service: str = "", capacity: Optional[int] = None,
                 enabled: Optional[bool] = None):
        self.service = service or os.environ.get("DYN_SERVICE", "dynamo")
        self.enabled = flight_enabled() if enabled is None else enabled
        cap = capacity or _env_int("DYN_FLIGHT_CAPACITY", 4096)
        self.sigma = _env_float("DYN_FLIGHT_SIGMA", 4.0)
        self.steady_after = _env_int("DYN_FLIGHT_STEADY_STEPS", 64)
        self.storm_threshold = _env_int("DYN_FLIGHT_STORM", 4)
        self._ring: collections.deque[StepRecord] = collections.deque(
            maxlen=max(16, cap))
        self._lock = threading.Lock()
        self._seq = 0
        #: PER-KIND rolling step-time baselines (non-empty steps) with
        #: running moments — O(1) per record, never a full-window scan.
        #: Per kind, not pooled: a routine 30 ms prefill chunk after a
        #: stretch of ~1 ms pipelined decode steps is NOT a slow step,
        #: and a pooled σ would tag it on every burst boundary.
        self._base: dict[str, list] = {}  # kind -> [deque, sum, sq]
        #: rolling preemption counts for the storm window
        self._storm: collections.deque[int] = collections.deque(
            maxlen=STORM_WINDOW)
        self._storm_sum = 0
        self.anomaly_counts: dict[str, int] = {}
        #: merged [lo, hi] seq intervals snapshots have actually RETURNED
        #: (every slice is seq-contiguous), and the count of records the
        #: ring evicted while never inside any of them — i.e. dropped
        #: before EVER being served. A high-water mark would be wrong
        #: here: an ``n=1`` poll returns only the newest record, and
        #: marking everything older as served would zero the very signal
        #: the attribution join keys its ``incomplete`` flag on
        #: (dynamo_flight_records_dropped_total). The list stays tiny in
        #: practice (pollers repeat/extend one window); a hard cap merges
        #: the closest pair so it can never grow unbounded.
        self._served: list[list[int]] = []
        self.records_dropped_total = 0
        #: external gauges (disagg handler sets onboard/restore inflight;
        #: read at record time so every step carries the current value)
        self.gauges: dict[str, int] = {}
        self._jsonl_path = os.environ.get("DYN_STEP_JSONL") or None

    # ------------------------------------------------------------ recording

    def steady(self) -> bool:
        """Past the warm-up record count — the ONE signal both the
        ``compile-steady`` tag and the engine's steady-state-compile
        WARNING key on, so the tag and the log can never disagree."""
        return self._seq > self.steady_after

    @property
    def seq_now(self) -> int:
        """Latest assigned record seq (0 before any record) — span
        attributes snapshot it to bound a request's step interval."""
        return self._seq

    def set_gauge(self, name: str, value: int) -> None:
        self.gauges[name] = value

    def bump_gauge(self, name: str, delta: int) -> None:
        self.gauges[name] = max(0, self.gauges.get(name, 0) + delta)

    def _baseline(self, kind: str) -> tuple[int, float, float]:
        b = self._base.get(kind)
        if b is None:
            return 0, 0.0, 0.0
        dq, s, sq = b
        n = len(dq)
        if n == 0:
            return 0, 0.0, 0.0
        mean = s / n
        var = max(0.0, sq / n - mean * mean)
        return n, mean, math.sqrt(var)

    def record(self, kind: str, wall_ms: float, **fields) -> (
            Optional[StepRecord]):
        """Append one step record, computing its anomaly tags inline.
        Returns the record (None when recording is disabled)."""
        if not self.enabled:
            return None
        rec = StepRecord(kind=kind, wall_ms=float(wall_ms), t=time.time(),
                         **fields)
        if self.gauges:
            rec.onboard_inflight = rec.onboard_inflight or self.gauges.get(
                "onboard_inflight", 0)
            rec.restore_inflight = rec.restore_inflight or self.gauges.get(
                "restore_inflight", 0)
        with self._lock:
            self._seq += 1
            rec.seq = self._seq
            # ---- tags (computed BEFORE this record joins the baseline, so
            # an outlier can't raise the very threshold it must cross)
            n, mean, std = self._baseline(kind)
            if (kind != "empty" and n >= BASELINE_MIN_SAMPLES
                    and rec.wall_ms > mean
                    + max(self.sigma * std, SLOW_FLOOR_MS)):
                rec.tags.append(TAG_SLOW)
            if rec.compile_s > 0:
                rec.tags.append(TAG_COMPILE)
                if self.steady():
                    rec.tags.append(TAG_COMPILE_STEADY)
            preempts = rec.preempt_swap + rec.preempt_recompute
            self._storm_sum += preempts
            if len(self._storm) == self._storm.maxlen:
                self._storm_sum -= self._storm[0]
            self._storm.append(preempts)
            if preempts and self._storm_sum >= self.storm_threshold:
                rec.tags.append(TAG_PREEMPT_STORM)
            if rec.starved_decode > 0:
                rec.tags.append(TAG_STARVED)
            if kind == "empty":
                rec.tags.append(TAG_EMPTY)
            for t in rec.tags:
                self.anomaly_counts[t] = self.anomaly_counts.get(t, 0) + 1
            # ---- baseline update (empty bubbles excluded: their duration
            # is a wait, not a step time)
            if kind != "empty":
                b = self._base.get(kind)
                if b is None:
                    b = self._base[kind] = [
                        collections.deque(maxlen=BASELINE_WINDOW), 0.0, 0.0]
                dq = b[0]
                if len(dq) == dq.maxlen:
                    old = dq[0]
                    b[1] -= old
                    b[2] -= old * old
                dq.append(rec.wall_ms)
                b[1] += rec.wall_ms
                b[2] += rec.wall_ms * rec.wall_ms
            if len(self._ring) == self._ring.maxlen:
                evicted = self._ring[0].seq
                # retire intervals wholly below the eviction frontier
                while self._served and self._served[0][1] < evicted:
                    self._served.pop(0)
                if not (self._served
                        and self._served[0][0] <= evicted
                        <= self._served[0][1]):
                    self.records_dropped_total += 1
            self._ring.append(rec)
        path = self._jsonl_path
        if path:
            try:
                with open(path, "a") as f:
                    f.write(json.dumps(rec.to_dict()) + "\n")
            except OSError:
                self._jsonl_path = None  # never retry a broken sink per step
        return rec

    # ------------------------------------------------------------- reading

    def _mark_served(self, lo: int, hi: int) -> None:
        """Fold one returned contiguous seq range into the served-interval
        list (caller holds the lock)."""
        merged = []
        for iv in self._served:
            if iv[1] + 1 < lo or hi + 1 < iv[0]:
                merged.append(iv)
            else:  # overlap/adjacency: absorb
                lo, hi = min(lo, iv[0]), max(hi, iv[1])
        merged.append([lo, hi])
        merged.sort()
        while len(merged) > 64:  # bounded: fuse the closest gap (the
            gaps = [(merged[i + 1][0] - merged[i][1], i)  # undercounted
                    for i in range(len(merged) - 1)]      # drop is tiny)
            _, i = min(gaps)
            merged[i][1] = merged[i + 1][1]
            del merged[i + 1]
        self._served = merged

    def snapshot(self, n: Optional[int] = None,
                 since: int = 0) -> list[dict]:
        """Newest-last list of record dicts (the whole ring by default).

        ``since``: only records with ``seq > since`` — the incremental
        cursor behind ``GET /v1/fleet/steps?since=`` (pollers re-fetch
        only what they have not seen). Only the records actually RETURNED
        count as served for the dropped-before-served accounting — and
        they are marked under the SAME lock hold as the copy, so a
        concurrent record() eviction can never count a record this query
        is in the middle of serving as dropped-unserved."""
        with self._lock:
            recs = list(self._ring)
            if since > 0:
                recs = [r for r in recs if r.seq > since]
            if n is not None and n > 0:
                recs = recs[-n:]
            if recs:
                self._mark_served(recs[0].seq, recs[-1].seq)
        return [r.to_dict() for r in recs]

    def first_seq(self) -> int:
        """Oldest seq still in the ring (0 when empty) — the attribution
        join compares it against a request's step interval to detect a
        ring wrap (``incomplete=true``)."""
        with self._lock:
            return self._ring[0].seq if self._ring else 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def summary(self) -> dict:
        """Aggregate view for ``dynctl top``: step counts, rolling wall
        p50/p95, tok/s over the ring, anomaly counts, latest queue/tier
        state."""
        with self._lock:
            recs = list(self._ring)
            anomalies = dict(self.anomaly_counts)
            total = self._seq
        from dynamo_tpu.observability.stats import quantile

        steps = [r for r in recs if r.kind != "empty"]
        walls = [r.wall_ms for r in steps]
        tok_s = 0.0
        if len(steps) >= 2:
            span = steps[-1].t - steps[0].t
            if span > 0:
                tok_s = sum(r.tokens for r in steps) / span
        last = recs[-1] if recs else StepRecord()
        return {
            "service": self.service,
            "instance": _INSTANCE_ID,
            "enabled": self.enabled,
            "steps_total": total,
            "steps_in_ring": len(steps),
            "first_seq": recs[0].seq if recs else 0,
            "last_seq": last.seq,
            "last_t": last.t,
            "dropped_unserved": self.records_dropped_total,
            "wall_p50_ms": round(quantile(walls, 0.50) or 0.0, 3),
            "wall_p95_ms": round(quantile(walls, 0.95) or 0.0, 3),
            "tok_s": round(tok_s, 1),
            "tokens_in_ring": sum(r.tokens for r in steps),
            "anomalies": anomalies,
            "waiting": last.waiting,
            "swapped": last.swapped,
            "running": last.running,
            "kv_tiers": dict(last.kv_tiers),
            "onboard_inflight": self.gauges.get("onboard_inflight", 0),
            "restore_inflight": self.gauges.get("restore_inflight", 0),
        }

    def export_jsonl(self, path: str) -> int:
        """Dump the ring as JSONL; returns the line count."""
        recs = self.snapshot()
        with open(path, "w") as f:
            for d in recs:
                f.write(json.dumps(d) + "\n")
        return len(recs)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._base.clear()
            self._storm.clear()
            self._storm_sum = 0
            self.anomaly_counts = {}


# ------------------------------------------------------- process registry

#: name → WEAK ref to a recorder of THIS process; a process may host
#: several engines (mocker DP ranks), each with its own ring, all served
#: by one endpoint. Weak refs mean an engine discarded WITHOUT close()
#: (constructor failure after registration, bench/test churn) cannot pin
#: a ghost ring for the process lifetime — the owner holds the only
#: strong reference, and dead entries self-prune.
_registry: dict[str, "weakref.ref[FlightRecorder]"] = {}
_registry_lock = threading.Lock()


def register_recorder(name: str, rec: FlightRecorder) -> str:
    """Register under ``name`` (suffixing -2, -3… on collision); returns
    the name actually used."""
    with _registry_lock:
        for k in [k for k, r in _registry.items() if r() is None]:
            del _registry[k]
        base, n, final = name, 1, name
        while final in _registry and _registry[final]() is not rec:
            n += 1
            final = f"{base}-{n}"
        _registry[final] = weakref.ref(rec)
        return final


def unregister_recorder(name: str) -> None:
    with _registry_lock:
        _registry.pop(name, None)


def recorders() -> dict[str, FlightRecorder]:
    with _registry_lock:
        out = {}
        for name, ref in _registry.items():
            rec = ref()
            if rec is not None:
                out[name] = rec
        return out


# --------------------------------------------- control-plane fan-out layer


class FlightServeHandle:
    def __init__(self, runtime, key: str, cancel_serve):
        self._runtime = runtime
        self._key = key
        self._cancel = cancel_serve

    async def stop(self) -> None:
        try:
            self._runtime.drop_registration(self._key)
            await self._runtime.plane.kv_delete(self._key)
        finally:
            if self._cancel:
                await self._cancel()


async def serve_flight(runtime) -> FlightServeHandle:
    """Expose this process's flight recorders to fleet queries.

    Query wire: msgpack ``{"n": <records>, "since": <seq>}`` (n<=0 or
    absent → summaries only; since>0 → only records past that seq —
    the incremental-poll cursor) → ``{"service", "workers": {name:
    {"summary", "steps"}}}``. The discovery key rides the primary lease,
    so a dead worker drops out of the fan-out exactly like its serving
    endpoints (collector.py)."""
    lease = await runtime.primary_lease()
    subject = f"flight-{lease:x}"

    async def on_request(payload: bytes) -> bytes:
        try:
            q = msgpack.unpackb(payload, raw=False) or {}
        except Exception:
            q = {}
        n = int(q.get("n") or 0)
        since = int(q.get("since") or 0)
        workers = {}
        for name, rec in recorders().items():
            entry = {"summary": rec.summary()}
            if n > 0 or since > 0:
                entry["steps"] = rec.snapshot(n if n > 0 else None,
                                              since=since)
            workers[name] = entry
        return msgpack.packb({
            "service": os.environ.get("DYN_SERVICE", "dynamo"),
            "workers": workers,
        })

    cancel = await runtime.plane.serve(subject, on_request)
    key = f"{FLIGHT_PREFIX}{lease:x}"
    value = msgpack.packb(
        {"subject": subject,
         "service": os.environ.get("DYN_SERVICE", "dynamo")})
    await runtime.plane.kv_put(key, value, lease_id=lease)
    runtime.record_registration(key, value)
    logger.debug("flight query endpoint on %s", subject)
    return FlightServeHandle(runtime, key, cancel)


async def ensure_flight_endpoint(runtime) -> FlightServeHandle:
    """Idempotent per-runtime ``serve_flight`` (mirrors
    ensure_trace_endpoint: mocker ranks / engine roles register once)."""
    handle = getattr(runtime, "_flight_serve_handle", None)
    if handle is None:
        handle = await serve_flight(runtime)
        runtime._flight_serve_handle = handle
    return handle


async def fetch_fleet_steps(plane, n: int = 0, timeout: float = 2.0,
                            since: int = 0) -> dict:
    """Fan a step query out to every registered flight endpoint.

    Returns ``{"<lease-hex>/<name>": {"summary", "steps"?}}``. A slow or
    dead worker times out individually and is simply dropped — a partial
    fleet view beats none (same contract as fetch_trace). ``since``
    fetches only records past that seq (one cursor applied to every
    worker; per-worker cursors belong to the poller)."""
    try:
        entries = await plane.kv_get_prefix(FLIGHT_PREFIX)
    except Exception:
        logger.exception("flight discovery failed")
        return {}

    async def one(key: str, value: bytes) -> dict:
        try:
            meta = msgpack.unpackb(value, raw=False)
            raw = await asyncio.wait_for(
                plane.request(meta["subject"],
                              msgpack.packb({"n": n, "since": since}),
                              timeout=timeout),
                timeout + 0.5)
            resp = msgpack.unpackb(raw, raw=False) or {}
            lease_hex = key[len(FLIGHT_PREFIX):]
            return {f"{lease_hex}/{name}": entry
                    for name, entry in (resp.get("workers") or {}).items()}
        except Exception:
            return {}  # that worker is gone/slow; keep the rest

    results = await asyncio.gather(
        *(one(k, v) for k, v in entries.items()))
    merged: dict = {}
    for part in results:
        merged.update(part)
    return merged
