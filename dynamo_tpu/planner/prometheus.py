"""Prometheus metrics source for the planner.

Rebuild of the reference's frontend-scraping source (ref: components/
planner/src/dynamo/planner/utils/prometheus.py): each planner tick pulls
the frontend's ``/metrics`` text exposition and turns counter DELTAS over
the interval into an Observation — request rate, mean ISL/OSL (from the
llm_*_tokens_total counters), and mean TTFT/ITL-ish latency (from the
histogram sums/counts). No client library: the exposition format is three
trivial line shapes.
"""

from __future__ import annotations

import logging
import re
import time
from typing import Optional

from dynamo_tpu.planner.planner_core import Observation

logger = logging.getLogger("dynamo.planner.prom")

_LINE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+([^\s]+)$")

#: the routes whose latency histograms describe LLM generation — embeddings
#: or error routes would corrupt the ITL estimate (their latencies average
#: into the same metric name)
_LLM_ROUTES = ('route="chat"', 'route="completions"', 'route="responses"')


def parse_prometheus_text(text: str) -> dict[str, float]:
    """name{labels} → value, summing across label sets per metric name.

    Latency/TTFT histogram series are only summed for LLM-generation routes
    (chat/completions/responses); token counters carry only model labels and
    sum freely.
    """
    out: dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _LINE.match(line.strip())
        if not m:
            continue
        name, labels, value = m.groups()
        if (labels and "route=" in labels
                and not any(r in labels for r in _LLM_ROUTES)):
            continue
        try:
            out[name] = out.get(name, 0.0) + float(value)
        except ValueError:
            continue
    return out


#: counter/sum families whose per-interval deltas build an Observation
_DELTA_FAMILIES = (
    "dynamo_llm_requests_finished_total",
    "dynamo_llm_prompt_tokens_total",
    "dynamo_llm_completion_tokens_total",
    "dynamo_http_request_duration_seconds_sum",
    "dynamo_http_request_duration_seconds_count",
    "dynamo_http_time_to_first_token_seconds_sum",
    "dynamo_http_time_to_first_token_seconds_count",
)


def _observation_from_deltas(dt: float, d: dict[str, float]
                             ) -> Optional[Observation]:
    """Counter deltas over one interval → Observation (None when idle)."""
    finished = d.get("dynamo_llm_requests_finished_total", 0.0)
    if finished <= 0:
        return None  # idle interval: nothing to learn from
    prompt = d.get("dynamo_llm_prompt_tokens_total", 0.0)
    completion = d.get("dynamo_llm_completion_tokens_total", 0.0)
    d_lat_sum = d.get("dynamo_http_request_duration_seconds_sum", 0.0)
    d_lat_cnt = d.get("dynamo_http_request_duration_seconds_count", 0.0)
    d_ttft_sum = d.get("dynamo_http_time_to_first_token_seconds_sum", 0.0)
    d_ttft_cnt = d.get("dynamo_http_time_to_first_token_seconds_count", 0.0)
    ttft_ms = (1000.0 * d_ttft_sum / d_ttft_cnt) if d_ttft_cnt else None
    osl = completion / finished
    itl_ms = None
    if d_lat_cnt and ttft_ms is not None and osl > 1:
        mean_lat_ms = 1000.0 * d_lat_sum / d_lat_cnt
        itl_ms = max(0.0, (mean_lat_ms - ttft_ms) / (osl - 1))
    return Observation(
        request_rate=finished / max(1e-9, dt),
        isl=prompt / finished,
        osl=osl,
        ttft_ms=ttft_ms,
        itl_ms=itl_ms,
    )


class PrometheusMetricsSource:
    """async () -> Observation|None over a frontend /metrics URL."""

    #: counter families whose raw monotonic values feed the deltas — the
    #: reset detector watches exactly these (histogram means ride on them)
    _COUNTERS = (
        "dynamo_llm_requests_finished_total",
        "dynamo_llm_prompt_tokens_total",
        "dynamo_llm_completion_tokens_total",
        "dynamo_http_request_duration_seconds_count",
        "dynamo_http_time_to_first_token_seconds_count",
    )

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        if not self.url.endswith("/metrics"):
            self.url += "/metrics"
        self._prev: Optional[dict[str, float]] = None
        self._prev_t: float = 0.0
        #: raw text of the last successful scrape (the autoscaler's
        #: per-class TTFT tracker parses histogram buckets from it)
        self.last_text: Optional[str] = None
        #: scrape failures + counter resets observed (loop telemetry)
        self.scrape_failures = 0
        self.resets = 0

    async def _fetch(self) -> Optional[dict[str, float]]:
        import aiohttp

        try:
            async with aiohttp.ClientSession() as s:
                async with s.get(self.url,
                                 timeout=aiohttp.ClientTimeout(total=5)) as r:
                    if r.status != 200:
                        self.scrape_failures += 1
                        return None
                    text = await r.text()
                    self.last_text = text
                    return parse_prometheus_text(text)
        except Exception:
            self.scrape_failures += 1
            logger.warning("metrics scrape failed: %s", self.url)
            return None

    async def sample(self) -> Optional[tuple[float, dict[str, float]]]:
        """One scrape → ``(dt_seconds, counter_deltas)``, or None when the
        fetch failed, this was the first sample, or a counter reset was
        detected. The raw-delta form exists so a fleet of replica scrapes
        (:class:`MultiPrometheusSource`) can be SUMMED before the ratio
        math — averaging per-replica Observations would weight a nearly
        idle replica the same as a loaded one."""
        cur = await self._fetch()
        now = time.monotonic()
        if cur is None:
            return None
        prev, prev_t = self._prev, self._prev_t
        self._prev, self._prev_t = cur, now
        if prev is None:
            return None  # first sample: no deltas yet
        # counter-reset detection: a restarted frontend starts every
        # counter back at ~0, so cur < prev. The per-delta max(0, ·) below
        # already clamps each counter individually, but a PARTIAL interval
        # (reset mid-window: small-but-positive deltas against pre-restart
        # latency sums) would still feed the predictor a garbage sample —
        # skip the whole interval and rebase on the fresh counters.
        if any(cur.get(n, 0.0) < prev.get(n, 0.0) for n in self._COUNTERS):
            self.resets += 1
            logger.warning("counter reset detected (frontend restart?); "
                           "skipping one observation interval")
            return None
        deltas = {n: max(0.0, cur.get(n, 0.0) - prev.get(n, 0.0))
                  for n in _DELTA_FAMILIES}
        return max(1e-9, now - prev_t), deltas

    async def __call__(self) -> Optional[Observation]:
        s = await self.sample()
        if s is None:
            return None
        return _observation_from_deltas(*s)


class MultiPrometheusSource:
    """Fleet front-door source: one :class:`PrometheusMetricsSource` per
    frontend replica URL, per-replica counter deltas summed into ONE
    Observation per tick (docs/robustness.md "Front door").

    Per-replica ``_prev`` snapshots keep reset detection replica-local —
    one restarted frontend rebases alone instead of poisoning the whole
    fleet sample — and a dead replica simply drops out of the sum, so the
    autoscaler keeps seeing the surviving replicas' traffic during a
    front-door kill. ``last_text`` concatenates the expositions of the
    replicas that answered THIS tick (a dead replica's stale text is
    excluded); replica-labeled series keep their label sets distinct, so
    downstream per-class parsers (autoscale/observe.py) sum histogram
    buckets and take worst-case gauges instead of double-counting.
    """

    def __init__(self, urls: list[str]):
        if not urls:
            raise ValueError("MultiPrometheusSource needs at least one URL")
        self.sources = [PrometheusMetricsSource(u) for u in urls]
        self.last_text: Optional[str] = None
        #: ticks on which NO replica could be scraped (fleet-level
        #: blindness — one dead replica of several is not a failure here;
        #: per-replica counts live on ``self.sources[i].scrape_failures``)
        self.scrape_failures = 0
        self.resets = 0

    async def __call__(self) -> Optional[Observation]:
        import asyncio

        before = [s.scrape_failures for s in self.sources]
        samples = await asyncio.gather(*(s.sample() for s in self.sources))
        answered = [s for s, b in zip(self.sources, before)
                    if s.scrape_failures == b]
        if not answered:
            self.scrape_failures += 1
        self.resets = sum(s.resets for s in self.sources)
        texts = [s.last_text for s in answered if s.last_text]
        self.last_text = "\n".join(texts) if texts else None
        live = [x for x in samples if x is not None]
        if not live:
            return None
        combined: dict[str, float] = {}
        for _, d in live:
            for k, v in d.items():
                combined[k] = combined.get(k, 0.0) + v
        # replica scrape windows are near-identical (same tick); the mean
        # interval turns the summed finished-count into a fleet rate
        dt = sum(t for t, _ in live) / len(live)
        return _observation_from_deltas(dt, combined)
