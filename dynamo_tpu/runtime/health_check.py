"""Canary health checks: probe idle endpoints, drive instance health state.

Rebuild of the reference's health-check manager (ref: lib/runtime/src/
health_check.rs:20-579): each watched endpoint gets a canary payload; when an
instance has been idle longer than the check interval, the manager sends the
canary directly to it. Failures mark the instance down on the shared Client
(so routing skips it); a later success restores it.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Optional

logger = logging.getLogger("dynamo.health")


def default_canary_payload() -> dict:
    """A minimal *valid* 1-token generate request.

    Engine generate endpoints parse their input with
    ``PreprocessedRequest.from_wire``, so the canary must be a real request —
    a bare ``{"health_check": true}`` dict would fail parsing on every probe
    and mark healthy workers down (ref behavior: health_check.rs canary
    payloads are per-endpoint valid requests).
    """
    from dynamo_tpu.protocols import (PreprocessedRequest, SamplingOptions,
                                      StopConditions)

    req = PreprocessedRequest(
        model="__health_check__",
        token_ids=[0],
        stop_conditions=StopConditions(max_tokens=1, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
        annotations=["health_check"],
    )
    return req.to_wire()


@dataclass
class HealthCheckConfig:
    #: probe an instance after this much idle time (s)
    check_interval_s: float = 10.0
    #: canary request timeout (s)
    timeout_s: float = 5.0
    #: consecutive failures before marking down
    failure_threshold: int = 2
    #: payload sent as the canary request (engine-specific; defaults to a
    #: valid 1-token generate request)
    payload: Any = field(default_factory=default_canary_payload)

    @staticmethod
    def from_runtime(config, payload: Any = None) -> "HealthCheckConfig":
        """Derive probe cadence/threshold from the layered RuntimeConfig
        (``DYN_HEALTH_CHECK_INTERVAL`` / ``DYN_HEALTH_CHECK_FAILURES``)."""
        kw = dict(check_interval_s=config.health_check_interval,
                  failure_threshold=config.health_check_failures)
        if payload is not None:
            kw["payload"] = payload
        return HealthCheckConfig(**kw)


class HealthCheckManager:
    """Probes every instance of one endpoint client on a timer."""

    def __init__(self, client, config: Optional[HealthCheckConfig] = None):
        self.client = client
        self.cfg = config or HealthCheckConfig()
        self._failures: dict[int, int] = {}
        self._last_ok: dict[int, float] = {}
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()

    def note_activity(self, instance_id: int) -> None:
        """Real traffic succeeded on this instance — reset its canary clock."""
        self._last_ok[instance_id] = time.monotonic()
        self._failures.pop(instance_id, None)

    async def start(self) -> "HealthCheckManager":
        self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    async def stop(self) -> None:
        self._stop.set()
        if self._task:
            await self._task

    async def _loop(self) -> None:
        interval = max(0.5, self.cfg.check_interval_s / 4)
        while not self._stop.is_set():
            try:
                await self._probe_idle()
            except Exception:
                logger.exception("health probe iteration failed")
            try:
                await asyncio.wait_for(self._stop.wait(), interval)
            except asyncio.TimeoutError:
                pass

    async def _probe_idle(self) -> None:
        now = time.monotonic()
        due = [iid for iid in self.client.instance_ids()
               if now - self._last_ok.get(iid, 0.0) >= self.cfg.check_interval_s]
        if due:
            # concurrent probes: one wedged instance must not stall the rest
            await asyncio.gather(*(self._probe(iid) for iid in due))

    async def _probe_once(self, iid: int) -> None:
        stream = await self.client.generate(self.cfg.payload, mode="direct",
                                            instance_id=iid)
        async for _ in stream:  # drain; any frame counts as life
            break

    async def _probe(self, iid: int) -> None:
        try:
            # one timeout covers connect *and* first frame — a worker that
            # accepts the canary but never yields must still count as a failure
            await asyncio.wait_for(self._probe_once(iid), self.cfg.timeout_s)
            self.note_activity(iid)
            # a previously-down instance that answers is routable again
            self.client.report_instance_up(iid)
        except Exception as e:
            n = self._failures.get(iid, 0) + 1
            self._failures[iid] = n
            logger.warning("canary failed for %x (%d/%d): %r", iid, n,
                           self.cfg.failure_threshold, e)
            if n >= self.cfg.failure_threshold:
                self.client.report_instance_down(iid)
