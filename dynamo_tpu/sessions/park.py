"""Worker-side session KV parking/restore (the ``kv_session`` endpoint).

An idle session's prompt prefix should ride the tier ladder DOWN (G2/G3 →
G4 object store) instead of dying by LRU, and ride back UP (G4 → host
tier) before the session's next turn arrives — docs/sessions.md "Parking".
The frontend's session reaper drives ``op=park`` at the session's affinity
worker when the idle threshold passes; a returning turn fires ``op=restore``
concurrent with tokenization, so by the time admission builds its onboard
plan the prefix is host-resident and attaches without a G4 round trip.

Keying: parked blocks use the session prefix's canonical hash chain
(``dynamo_tpu.tokens`` block/sequence hashes) — the same key domain every
tier and the router's radix speak. The "session scope" lives in the
frontend registry (which chain belongs to which session); the G4 replica
itself stays fleet-readable, so a parked session's prefix doubles as
shared prefix cache for any same-prefix traffic via the sentinel radix.

The handler degrades to an explicit no-op without a KVBM (mocker fleets,
caching-off engines): fleet drives carry session traffic end-to-end and
the frontend sees honest zeros instead of wire errors.
"""

from __future__ import annotations

import asyncio
import logging

from dynamo_tpu.tokens import KV_HASH_SEED, TokenBlockSequence

logger = logging.getLogger("dynamo.sessions.park")

#: endpoint name on the worker component (sibling of generate/kv_pull)
SESSION_ENDPOINT = "kv_session"


def session_prefix_hashes(token_ids, block_size: int) -> list[int]:
    """The sequence-hash chain of a prompt's COMPLETE blocks — the keys a
    park/restore addresses. The ragged tail block never got a KV identity,
    so it is never parked."""
    if not token_ids or block_size <= 0:
        return []
    seq = TokenBlockSequence.from_tokens(token_ids, block_size, KV_HASH_SEED)
    return [b.sequence_hash for b in seq.blocks]


class SessionKvHandler:
    """Serves ``kv_session`` ops against this worker's KVBM tiers.

    ``engine=None`` (or an engine without a KVBM) is the stub arm: every op
    succeeds with ``blocks=0`` so session traffic runs unchanged on mocker
    fleets and caching-off workers.
    """

    def __init__(self, engine=None, metrics=None):
        self.engine = engine
        self._parked = self._restored = None
        if metrics is not None:
            self._parked = metrics.counter(
                "session_kv_blocks_total",
                "session KV blocks moved by this worker's kv_session "
                "endpoint, by op (park|restore)")

    def _kvbm(self):
        return getattr(self.engine, "kvbm", None) if self.engine else None

    def _block_size(self) -> int:
        args = getattr(self.engine, "args", None)
        return getattr(args, "block_size", 0) if args is not None else 0

    def _park(self, hashes: list[int]) -> tuple[int, int]:
        """Publish the leading locally-resident run to G4. Returns
        (published, covered): ``covered`` counts blocks now G4-resident
        (published this call or already there) — the number the session
        can rely on for its return. Stops at the first block no local
        tier holds: G4 onboarding attaches contiguous prefixes only, so a
        gapped park would strand everything behind the hole."""
        kvbm = self._kvbm()
        published = covered = 0
        try:
            for h in hashes:
                if kvbm.remote_resident([h]):
                    covered += 1
                    continue
                e = kvbm.get_local(h)
                if e is None:
                    break
                if kvbm.publish_remote(h, e[0], e[1], drain=False):
                    published += 1
                    covered += 1
                else:
                    break  # G4 not armed: nothing downstream can land
        finally:
            kvbm.drain_remote()
        return published, covered

    async def generate(self, request: dict, ctx=None):
        op = (request or {}).get("op")
        token_ids = (request or {}).get("token_ids") or []
        if op not in ("park", "restore"):
            yield {"error": f"unknown kv_session op {op!r}"}
            return
        kvbm = self._kvbm()
        bs = self._block_size()
        if kvbm is None or bs <= 0:
            yield {"ok": True, "op": op, "blocks": 0, "stub": True}
            return
        hashes = session_prefix_hashes(token_ids, bs)
        if not hashes:
            yield {"ok": True, "op": op, "blocks": 0}
            return
        # tier I/O is blocking (disk reads, object-store round trips):
        # never on the serving event loop
        if op == "park":
            published, covered = await asyncio.to_thread(self._park, hashes)
            if self._parked is not None and published:
                self._parked.inc(published, op="park")
            yield {"ok": True, "op": "park", "blocks": covered,
                   "published": published, "prefix_blocks": len(hashes)}
        else:
            landed = await asyncio.to_thread(kvbm.fetch_remote, hashes)
            if self._parked is not None and landed:
                self._parked.inc(landed, op="restore")
            yield {"ok": True, "op": "restore", "blocks": landed,
                   "prefix_blocks": len(hashes)}
