"""Typed, layered runtime configuration (``DYN_*``).

Figment-style layering like the reference (ref: lib/runtime/src/config.rs:
1-608 — defaults < config file < environment, typed extraction with clear
errors):

1. dataclass defaults,
2. an optional config file (``DYN_CONFIG_FILE``: TOML or JSON),
3. ``DYN_<FIELD>`` environment variables (highest precedence).

Values are coerced to the field's declared type; a bad value or an unknown
key in the config file raises :class:`ConfigError` naming the offender —
a typo'd knob must fail loudly at startup, not silently use a default.

Env surface:

- ``DYN_CONTROL_PLANE``    — ``host:port`` of dynctl; unset = in-process.
  May be a comma-separated list (``primary:port,standby:port``) when a
  warm-standby dynctl runs (``--standby-of``): clients fail over by
  cycling the list on reconnect.
- ``DYN_LEASE_TTL``        — primary lease TTL seconds (default 10).
- ``DYN_NAMESPACE``        — default namespace (default ``dynamo``).
- ``DYN_REQUEST_TIMEOUT``  — request-plane ack timeout seconds.
- ``DYN_HEALTH_CHECK_INTERVAL`` / ``DYN_HEALTH_CHECK_FAILURES`` — canary
  probe cadence and unroutable threshold.
- ``DYN_SYSTEM_PORT``      — system status server port (0 = disabled).
- ``DYN_LOG``              — log level (default info).
- ``DYN_LOGGING_JSONL``    — JSONL log lines when truthy.
- ``DYN_CONFIG_FILE``      — path to a TOML/JSON file with the same keys
  (lower-case field names).

Overload protection / robustness (docs/robustness.md):

- ``DYN_REQUEST_DEADLINE``    — default e2e deadline seconds (frontend).
- ``DYN_MAX_INFLIGHT`` / ``DYN_MAX_QUEUE`` — frontend admission caps
  (total / per-model); excess gets 429 + ``Retry-After``.
- ``DYN_WORKER_MAX_INFLIGHT`` — per-endpoint worker admission cap; excess
  is rejected with a terminal "overloaded" stream error.
- ``DYN_CIRCUIT_THRESHOLD``   — consecutive transport failures that open a
  client's per-instance circuit breaker.
- ``DYN_DRAIN_TIMEOUT``       — graceful SIGTERM drain bound (seconds).
- ``DYN_CHAOS`` / ``DYN_CHAOS_SEED`` — seeded fault injection
  (runtime/chaos.py spec grammar).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import typing
from dataclasses import dataclass, field
from typing import Optional


class ConfigError(Exception):
    """A configuration value failed validation; message names the field."""


_TRUTHY = {"1", "true", "yes", "on"}
_FALSY = {"0", "false", "no", "off", ""}


def _coerce(name: str, value, typ):
    """Coerce ``value`` (often a string from the env) to ``typ``."""
    origin = typing.get_origin(typ)
    if origin is typing.Union:  # Optional[T]
        args = [a for a in typing.get_args(typ) if a is not type(None)]
        if value is None:
            return None
        return _coerce(name, value, args[0])
    if value is None:  # null for a non-Optional field: fail loudly
        raise ConfigError(f"config field '{name}': null is not allowed")
    try:
        if typ is bool:
            if isinstance(value, bool):
                return value
            s = str(value).strip().lower()
            if s in _TRUTHY:
                return True
            if s in _FALSY:
                return False
            raise ValueError(f"not a boolean: {value!r}")
        if typ is int:
            return int(str(value).strip())
        if typ is float:
            return float(str(value).strip())
        if typ is str:
            return str(value)
    except (TypeError, ValueError) as e:
        raise ConfigError(f"config field '{name}': {e}") from None
    return value


@dataclass
class RuntimeConfig:
    """Process-wide runtime knobs (ref: config.rs RuntimeConfig)."""

    #: dynctl address (host:port); None = in-process control plane
    control_plane_address: Optional[str] = None
    #: primary lease TTL seconds; instances vanish this long after a crash
    lease_ttl: float = 10.0
    namespace: str = "dynamo"
    #: request-plane ack timeout (seconds)
    request_timeout: float = 10.0
    #: canary health-check cadence (seconds) and failure threshold
    health_check_interval: float = 30.0
    health_check_failures: int = 3
    #: system status server port (0 = disabled)
    system_port: int = 0
    #: KV-load fraction above which routing skips a worker (WorkerMonitor);
    #: None = load monitoring off (ref: worker_monitor.rs busy_threshold)
    busy_threshold: Optional[float] = None
    #: default end-to-end request deadline (seconds) applied by the frontend
    #: when the client sends no ``X-Request-Timeout-Ms``; None = no deadline
    request_deadline: Optional[float] = None
    #: frontend admission: max concurrent in-flight HTTP LLM requests
    #: (0 = unbounded); excess gets 429 + Retry-After
    max_inflight: int = 0
    #: frontend admission: max in-flight requests PER MODEL (0 = unbounded)
    max_queue: int = 0
    #: worker admission: max concurrent requests per served endpoint
    #: (0 = unbounded); excess is rejected with a terminal "overloaded"
    #: stream error so Migration does not burn its budget on a full fleet
    worker_max_inflight: int = 0
    #: consecutive transport failures that OPEN a client's per-instance
    #: circuit breaker (canary success half-closes it; a real success closes)
    circuit_threshold: int = 3
    #: graceful SIGTERM drain bound (seconds): in-flight streams get this
    #: long to finish before shutdown forces them
    drain_timeout: float = 30.0
    #: proactive death handling (docs/robustness.md): after an instance's
    #: discovery key is deleted, a live stream from it is failed RETRYABLY
    #: once it has produced no frames for this long. The grace window is
    #: what distinguishes a gracefully-DRAINING worker (deregisters first,
    #: keeps streaming until done — its streams must not be broken) from a
    #: lease-expired corpse (streams silent since death). 0 = break
    #: immediately on the delete event.
    worker_lost_grace: float = 5.0

    def __post_init__(self):
        if self.busy_threshold is not None and not 0 < self.busy_threshold <= 1:
            raise ConfigError(
                "config field 'busy_threshold': must be in (0, 1]")
        if self.lease_ttl <= 0:
            raise ConfigError("config field 'lease_ttl': must be > 0")
        if self.request_timeout <= 0:
            raise ConfigError("config field 'request_timeout': must be > 0")
        if self.health_check_failures < 1:
            raise ConfigError(
                "config field 'health_check_failures': must be >= 1")
        if self.health_check_interval <= 0:
            raise ConfigError(
                "config field 'health_check_interval': must be > 0")
        if not self.namespace:
            raise ConfigError("config field 'namespace': must be non-empty")
        if self.request_deadline is not None and self.request_deadline <= 0:
            raise ConfigError(
                "config field 'request_deadline': must be > 0")
        for fname in ("max_inflight", "max_queue", "worker_max_inflight"):
            if getattr(self, fname) < 0:
                raise ConfigError(f"config field '{fname}': must be >= 0")
        if self.circuit_threshold < 1:
            raise ConfigError(
                "config field 'circuit_threshold': must be >= 1")
        if self.drain_timeout <= 0:
            raise ConfigError("config field 'drain_timeout': must be > 0")
        if self.worker_lost_grace < 0:
            raise ConfigError(
                "config field 'worker_lost_grace': must be >= 0")

    # -- layered loading -----------------------------------------------------

    #: field name → env var (control_plane_address keeps its historical name)
    _ENV_OVERRIDES = {
        "control_plane_address": "DYN_CONTROL_PLANE",
        "health_check_interval": "DYN_HEALTH_CHECK_INTERVAL",
        "health_check_failures": "DYN_HEALTH_CHECK_FAILURES",
        "busy_threshold": "DYN_BUSY_THRESHOLD",
    }

    @classmethod
    def load(cls, config_file: Optional[str] = None,
             env: Optional[dict] = None) -> "RuntimeConfig":
        """defaults < config file < DYN_* env (highest wins)."""
        env = os.environ if env is None else env
        # `from __future__ import annotations` stringifies field.type;
        # resolve the real types for coercion
        hints = typing.get_type_hints(cls)
        fields = {f.name: f for f in dataclasses.fields(cls)
                  if not f.name.startswith("_")}
        values: dict = {}

        path = config_file or env.get("DYN_CONFIG_FILE")
        if path:
            file_vals = cls._read_file(path)
            unknown = set(file_vals) - set(fields)
            if unknown:
                raise ConfigError(
                    f"unknown config key(s) in {path}: {sorted(unknown)}")
            values.update(file_vals)

        for name, f in fields.items():
            var = cls._ENV_OVERRIDES.get(name, f"DYN_{name.upper()}")
            if var in env:
                values[name] = env[var]

        coerced = {
            name: _coerce(name, values[name], hints[name])
            for name in values
        }
        return cls(**coerced)

    @staticmethod
    def _read_file(path: str) -> dict:
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise ConfigError(f"cannot read config file {path}: {e}") from None
        text = raw.decode()
        if path.endswith(".json"):
            try:
                return json.loads(text)
            except json.JSONDecodeError as e:
                raise ConfigError(f"bad JSON in {path}: {e}") from None
        try:
            try:
                import tomllib  # 3.11+
            except ModuleNotFoundError:
                import tomli as tomllib  # 3.10 fallback

            return tomllib.loads(text)
        except Exception as e:
            raise ConfigError(f"bad TOML in {path}: {e}") from None

    @staticmethod
    def from_env() -> "RuntimeConfig":
        return RuntimeConfig.load()


def apply_platform_env() -> None:
    """Honor an explicit ``JAX_PLATFORMS`` even though the container's
    sitecustomize imports jax at interpreter startup and pins the axon TPU
    plugin (by then the env var is too late — jax.config must be used).
    Without this, CPU-only smoke runs of the worker mains hang trying to
    reach a TPU tunnel they were told not to use."""
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    try:
        import jax

        jax.config.update("jax_platforms", want)
    except Exception:  # jax absent (pure control-plane processes): fine
        pass


_LOGGING_CONFIGURED = False


def setup_logging():
    global _LOGGING_CONFIGURED
    if _LOGGING_CONFIGURED:
        return
    _LOGGING_CONFIGURED = True
    apply_platform_env()
    level = os.environ.get("DYN_LOG", "info").upper()
    if os.environ.get("DYN_LOGGING_JSONL"):
        fmt = ('{"ts":"%(asctime)s","level":"%(levelname)s",'
               '"target":"%(name)s","rid":"%(rid)s","msg":"%(message)s"}')
    else:
        fmt = "%(asctime)s %(levelname)-7s %(name)s [%(rid)s]: %(message)s"
    logging.basicConfig(level=getattr(logging, level, logging.INFO), format=fmt)

    # every record carries the current request id (trace correlation across
    # frontend and worker processes — ref: logging.rs:150-215)
    class _RidFilter(logging.Filter):
        def filter(self, record):
            from dynamo_tpu.runtime.context import CURRENT_REQUEST

            ctx = CURRENT_REQUEST.get()
            record.rid = ctx.id[:16] if ctx is not None else "-"
            return True

    for h in logging.getLogger().handlers:
        h.addFilter(_RidFilter())
