"""Deterministic fault injection ("chaos") for recovery-path testing.

Every fault-tolerance claim in this codebase (migration, backoff, admission
shedding, deadline expiry) needs a way to be *proven* in fast tier-1 tests,
short of the slow process-kill suite. This module is that substrate: a
seeded, spec-driven injection registry with hook points compiled into the
hot paths (control-plane publish, response-plane sends, request dispatch,
engine step). When no spec is configured the hooks cost one global read.

Spec grammar (``DYN_CHAOS``)::

    DYN_CHAOS="plane.publish:drop=0.1;stream.send:delay=50ms;engine.step:error=0.05"

    spec    := entry (';' entry)*
    entry   := hook ':' action (',' action)*
    hook    := 'plane.publish' | 'stream.send' | 'request.dispatch'
             | 'engine.step' | 'kv.direct_pull' | 'worker.kill'
               (free-form: unknown hooks parse but never fire)
    action  := 'drop=' PROB | 'error=' PROB | 'delay=' DURATION
    PROB    := float in [0, 1]
    DURATION:= float with optional 'ms' or 's' suffix (default ms)

Semantics per hook:

- ``drop``  — the operation is lost. At ``plane.publish`` the message is
  silently not delivered (models pub/sub loss); at ``stream.send`` /
  ``request.dispatch`` the transport "dies" (raises :class:`ChaosError`,
  which the surrounding machinery surfaces as a retryable stream error —
  frames are never partially delivered, so token accounting stays exact).
- ``error`` — raise :class:`ChaosError` at the hook (models a crashed step
  / exploding handler).
- ``delay`` — sleep before the operation (models a slow network / stalled
  worker; only applied at async hooks).

Two hooks have special-case semantics:

- ``kv.direct_pull:error=P`` — a disagg direct KV pull or a migration
  restore pull fails; the puller degrades to host-staged placement or
  local recompute with exact token accounting (docs/robustness.md).
- ``worker.kill:error=P`` — rolled once per engine/mocker step while work
  is in flight; on fire the worker hard-dies SIGKILL-grade: the loop
  stops mid-decode, in-flight streams are never completed, no drain, no
  deregistration — death reaches the fleet only through lease expiry.
  Subprocess workers ``os._exit(137)``; in-process workers tear down
  their serve handles via ``ServeHandle.kill()`` and stop refreshing
  their lease.

Determinism: one ``random.Random(seed)`` (``DYN_CHAOS_SEED``, default 0)
drives every roll in hook-call order, so a fixed workload + fixed spec +
fixed seed reproduces the exact same fault sequence. Per-hook fire counts
are kept on the injector (``injector.counts``) so tests can assert faults
actually fired.
"""

from __future__ import annotations

import logging
import os
import random
from dataclasses import dataclass, field
from typing import Optional

logger = logging.getLogger("dynamo.chaos")


class ChaosError(Exception):
    """An injected fault. Never raised unless chaos is configured."""


class ChaosSpecError(ValueError):
    """The DYN_CHAOS spec string failed to parse; message names the part."""


@dataclass
class ChaosRule:
    """Parsed actions for one hook point."""

    drop: float = 0.0
    error: float = 0.0
    delay_s: float = 0.0


def _parse_duration(raw: str) -> float:
    """'50ms' / '2s' / bare number (ms) → seconds."""
    s = raw.strip().lower()
    mult = 0.001
    if s.endswith("ms"):
        s = s[:-2]
    elif s.endswith("s"):
        s, mult = s[:-1], 1.0
    try:
        v = float(s)
    except ValueError:
        raise ChaosSpecError(f"bad chaos duration {raw!r}") from None
    if v < 0:
        raise ChaosSpecError(f"negative chaos duration {raw!r}")
    return v * mult


def parse_chaos_spec(spec: str) -> dict[str, ChaosRule]:
    """Parse the ``DYN_CHAOS`` grammar; raises ChaosSpecError loudly —
    a typo'd fault plan silently injecting nothing defeats the point."""
    rules: dict[str, ChaosRule] = {}
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if ":" not in entry:
            raise ChaosSpecError(f"chaos entry {entry!r}: expected hook:action=value")
        hook, actions = entry.split(":", 1)
        hook = hook.strip()
        if not hook:
            raise ChaosSpecError(f"chaos entry {entry!r}: empty hook name")
        rule = rules.setdefault(hook, ChaosRule())
        for action in actions.split(","):
            action = action.strip()
            if "=" not in action:
                raise ChaosSpecError(f"chaos action {action!r}: expected name=value")
            name, value = (p.strip() for p in action.split("=", 1))
            if name in ("drop", "error"):
                try:
                    p = float(value)
                except ValueError:
                    raise ChaosSpecError(f"chaos action {action!r}: bad probability") from None
                if not 0.0 <= p <= 1.0:
                    raise ChaosSpecError(f"chaos action {action!r}: probability outside [0, 1]")
                setattr(rule, name, p)
            elif name == "delay":
                rule.delay_s = _parse_duration(value)
            else:
                raise ChaosSpecError(f"chaos action {action!r}: unknown action {name!r}")
    return rules


@dataclass
class ChaosInjector:
    """Seeded decision engine behind every hook point."""

    rules: dict[str, ChaosRule]
    seed: int = 0
    #: (hook, action) -> times fired; lets tests assert injection happened
    counts: dict[tuple[str, str], int] = field(default_factory=dict)

    def __post_init__(self):
        self._rng = random.Random(self.seed)

    @classmethod
    def from_spec(cls, spec: str, seed: int = 0) -> "ChaosInjector":
        return cls(rules=parse_chaos_spec(spec), seed=seed)

    def _fired(self, hook: str, action: str) -> None:
        key = (hook, action)
        self.counts[key] = self.counts.get(key, 0) + 1

    def should_drop(self, hook: str) -> bool:
        rule = self.rules.get(hook)
        if rule is None or rule.drop <= 0.0:
            return False
        if self._rng.random() < rule.drop:
            self._fired(hook, "drop")
            logger.debug("chaos: dropping at %s", hook)
            return True
        return False

    def should_error(self, hook: str) -> bool:
        rule = self.rules.get(hook)
        if rule is None or rule.error <= 0.0:
            return False
        if self._rng.random() < rule.error:
            self._fired(hook, "error")
            logger.debug("chaos: erroring at %s", hook)
            return True
        return False

    def delay_s(self, hook: str) -> float:
        rule = self.rules.get(hook)
        if rule is None or rule.delay_s <= 0.0:
            return 0.0
        self._fired(hook, "delay")
        return rule.delay_s

    async def pre(self, hook: str) -> None:
        """Apply delay-then-error at an async hook point. Raises ChaosError
        on an error roll; the caller handles ``should_drop`` itself because
        drop semantics differ per hook."""
        d = self.delay_s(hook)
        if d > 0.0:
            import asyncio

            await asyncio.sleep(d)
        if self.should_error(hook):
            raise ChaosError(f"injected error at {hook}")


#: None = chaos off (the common case: one global read per hook);
#: _UNSET = env not consulted yet
_UNSET = object()
_injector = _UNSET


def get_chaos() -> Optional[ChaosInjector]:
    """The process-wide injector, lazily built from ``DYN_CHAOS`` /
    ``DYN_CHAOS_SEED``; None when chaos is off."""
    global _injector
    if _injector is _UNSET:
        spec = os.environ.get("DYN_CHAOS")
        if spec:
            seed = int(os.environ.get("DYN_CHAOS_SEED", "0"))
            # decorrelate replicas of the same service: an operator fleet
            # shares one DYN_CHAOS_SEED, and identical seeds mean identical
            # roll SEQUENCES — every replica dies at nearly the same step,
            # turning per-worker kills into fleet-wide blackouts. Mixing in
            # the replica index keeps each process deterministic (fixed
            # seed + fixed index → same rolls) without the lockstep.
            replica = os.environ.get("DYN_REPLICA_INDEX")
            if replica is not None:
                try:
                    seed = seed * 1_000_003 + int(replica) + 1
                except ValueError:
                    pass
            _injector = ChaosInjector.from_spec(spec, seed=seed)
            logger.warning("chaos enabled (seed=%d): %s", seed, spec)
        else:
            _injector = None
    return _injector


def configure_chaos(spec: Optional[str], seed: int = 0) -> Optional[ChaosInjector]:
    """Install (or with spec=None, remove) the global injector — the test /
    bench entry point; overrides whatever the env said."""
    global _injector
    _injector = ChaosInjector.from_spec(spec, seed=seed) if spec else None
    return _injector
