"""``python -m dynamo_tpu.runtime.dynctl`` — run the control-plane server.

Single self-contained process replacing the reference's etcd + NATS pair for
TPU-VM deployments. Point every other process at it with
``DYN_CONTROL_PLANE=host:port``.

HA: run a second dynctl with ``--standby-of primary:port`` and set
``DYN_CONTROL_PLANE=primary:port,standby:port`` everywhere — the standby
mirrors durable state, promotes itself (fresh epoch) after sustained
primary silence, and fences/demotes the old primary if it comes back
(ref HA role: lib/runtime/src/transports/etcd.rs:35-770 replicated etcd).
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.runtime.config import setup_logging
from dynamo_tpu.runtime.control_plane import ControlPlaneServer


async def amain(host: str, port: int, persist: str = None,
                persist_interval: float = 5.0, standby_of: str = None,
                takeover_after: float = 6.0, replicate_interval: float = 1.0):
    server = ControlPlaneServer(host, port, persist_path=persist,
                                persist_interval=persist_interval,
                                standby_of=standby_of,
                                takeover_after=takeover_after,
                                replicate_interval=replicate_interval)
    addr = await server.start()
    print(f"dynctl listening on {addr}"
          + (" (standby)" if server.is_standby else ""), flush=True)

    stop = asyncio.Event()
    try:
        import signal
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop.set)
    except (ImportError, NotImplementedError):
        pass
    try:
        await stop.wait()  # SIGTERM → graceful stop → final state flush
    finally:
        await server.stop()


def main():
    setup_logging()
    ap = argparse.ArgumentParser(description="dynamo-tpu control plane server")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=6650)
    ap.add_argument("--persist", default=None, metavar="FILE",
                    help="durable-state file: discovery keys, object store "
                         "and stream tails survive a restart (leases do "
                         "not); snapshotted every --persist-interval s, "
                         "flushed on SIGTERM")
    ap.add_argument("--persist-interval", type=float, default=5.0)
    ap.add_argument("--standby-of", default=None, metavar="HOST:PORT",
                    help="run as a warm standby of this primary: mirror its "
                         "durable state, reject client ops, and promote to "
                         "primary (fresh epoch) after --takeover-after s of "
                         "primary silence; point clients at "
                         "DYN_CONTROL_PLANE=primary,standby")
    ap.add_argument("--takeover-after", type=float, default=6.0)
    ap.add_argument("--replicate-interval", type=float, default=1.0)
    args = ap.parse_args()
    asyncio.run(amain(args.host, args.port, args.persist,
                      args.persist_interval, args.standby_of,
                      args.takeover_after, args.replicate_interval))


if __name__ == "__main__":
    main()
