"""benchmarks/compare_gains.py comparison heuristics.

The phase-presence rules matter most: a brand-new bench phase (landed
before the baseline refresh) or a skipped phase must collapse to one
drift line per PHASE — per-key warn-spam buries the real regressions.
"""

from benchmarks.compare_gains import compare


BASE = {
    "extra": {
        "kernel_tok_s": 100.0,
        "chaos_smoke": {"chaos_ok": True, "p95_ms": 20.0},
        "qos": {"qos_ok": True, "int_ttft_p95_ms": 50.0},
    }
}


def _cur(**over):
    import copy

    cur = copy.deepcopy(BASE)
    cur["extra"].update(over)
    return cur


def test_no_changes_no_noise():
    regs, drifts = compare(BASE, BASE, 0.3)
    assert regs == [] and drifts == []


def test_gate_flip_and_directional_regression():
    cur = _cur(kernel_tok_s=50.0,
               chaos_smoke={"chaos_ok": False, "p95_ms": 40.0})
    regs, _ = compare(BASE, cur, 0.3)
    assert any("kernel_tok_s" in r for r in regs)
    assert any("chaos_ok" in r and "true → false" in r for r in regs)
    assert any("p95_ms" in r for r in regs)


def test_new_phase_in_gains_is_one_drift_line_not_spam():
    # a new phase (e.g. the flagship drive) lands before the baseline is
    # refreshed: its whole subtree must produce exactly ONE drift line
    # naming the phase, zero regressions, zero per-key lines
    cur = _cur(flagship={"flagship_ok": True, "lost_tokens": 0,
                         "hub_rpc_per_s": 20.0, "requests": 24,
                         "int_ttft_p95_ms": 21.0})
    regs, drifts = compare(BASE, cur, 0.3)
    assert regs == []
    assert len(drifts) == 1
    assert "flagship" in drifts[0] and "not in baseline" in drifts[0]


def test_baseline_phase_skipped_is_one_drift_line():
    cur = {"extra": {k: v for k, v in BASE["extra"].items()
                     if k != "qos"}}
    regs, drifts = compare(BASE, cur, 0.3)
    assert regs == []
    assert len(drifts) == 1
    assert "'qos'" in drifts[0] and "absent" in drifts[0]


def test_missing_keys_within_shared_phase_still_reported():
    cur = _cur(chaos_smoke={"chaos_ok": True})  # p95_ms gone, phase kept
    regs, drifts = compare(BASE, cur, 0.3)
    assert regs == []
    assert len(drifts) == 1
    assert "baseline keys absent" in drifts[0]
    assert "chaos_smoke.p95_ms" in drifts[0]
