"""In-process span recorder keyed by the runtime's W3C trace ids.

The runtime has propagated a ``traceparent`` on every ``Context`` hop since
the beginning (runtime/context.py) but nothing ever *recorded* a span, so
operators could not answer "where did this request spend its time" across
frontend → router → prefill → KV transfer → decode (ref survey §2,
``logging.rs`` span parenting). This module is that missing recorder:

- ``Span`` — one named phase of one request: trace id + span id + parent,
  wall-clock start/end (epoch seconds, so spans stitch across processes),
  free-form attributes.
- ``Tracer`` — per-process singleton holding a bounded ring buffer of ended
  spans, a ``MetricsRegistry`` of SLO histograms fed on span end
  (``dynamo_phase_seconds{phase=...}``, ``dynamo_ttft_seconds``,
  ``dynamo_itl_seconds``, ``dynamo_e2e_seconds``), and optional JSONL export
  (``DYN_TRACE_JSONL=<path>`` appends every ended span).

Parenting rules (W3C-compatible without changing Context wire semantics —
``to_wire`` still mints a fresh span id per hop, see
tests/test_runtime.py::test_traceparent_synthesis_and_child_spans):

1. same task/process: a new span parents to the task-local CURRENT_SPAN
   when it belongs to the same trace;
2. cross-process: the receiver's first span parents to the span id carried
   by the incoming ``traceparent`` — and the *sender* records that hop id
   as a zero-cost ``rpc.send`` span (``Tracer.record_hop``) so the chain
   frontend span → hop span → worker span stitches with no orphans.

Every API degrades to a no-op when the context carries no trace identity
(e.g. the engine's ``_NullCtx``) so call sites need no guards.
"""

from __future__ import annotations

import collections
import contextvars
import json
import logging
import os
import secrets
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Optional

from dynamo_tpu.runtime.context import CURRENT_REQUEST, Context
from dynamo_tpu.runtime.metrics import MetricsRegistry

#: task-local innermost live span — the parent for same-process child spans
CURRENT_SPAN: contextvars.ContextVar[Optional["Span"]] = (
    contextvars.ContextVar("dyn_current_span", default=None))

#: span name → extra (unlabeled) histogram fed on end, besides phase_seconds
_SLO_HISTOGRAMS = {
    "http.request": "e2e_seconds",
    "ttft": "ttft_seconds",
    "engine.ttft": "engine_ttft_seconds",
}

#: zero-duration marker spans (wire hops): stored for stitching but kept
#: out of the latency histograms — an always-zero phase whose count can
#: exceed request count under retries is dashboard noise
_NO_HISTOGRAM = {"rpc.send"}


_sample_warned = False


def trace_sample_rate() -> float:
    """``DYN_TRACE_SAMPLE`` (0.0–1.0) head-sampling rate for request spans;
    default 1.0 (record everything). Keeps the tracer + flight recorder
    bounded-overhead at fleet scale: unsampled traces record NOTHING in any
    process — the decision is a pure function of the trace id, so every hop
    agrees without a wire change."""
    global _sample_warned
    raw = os.environ.get("DYN_TRACE_SAMPLE")
    if not raw:
        return 1.0
    try:
        rate = float(raw)
    except ValueError:
        if not _sample_warned:
            _sample_warned = True
            logging.getLogger("dynamo.observability").warning(
                "ignoring malformed DYN_TRACE_SAMPLE=%r", raw)
        return 1.0
    return min(1.0, max(0.0, rate))


def trace_sampled(trace_or_request_id: str,
                  rate: Optional[float] = None) -> bool:
    """Deterministic head-sampling decision for a trace (or request) id:
    hash → [0,1) < rate. Identical on every process/hop for one id."""
    if rate is None:
        rate = trace_sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    h = zlib.crc32(str(trace_or_request_id).encode()) & 0xFFFFFFFF
    return h / 4294967296.0 < rate


def parse_traceparent(tp: Optional[str]) -> Optional[tuple[str, str]]:
    """``00-<trace>-<span>-<flags>`` → (trace_id, span_id), else None.
    Validity is delegated to ``Context._traceparent_valid`` — ONE parser
    rules both synthesis (ensure_traceparent) and recording, so the two
    can never drift into accepting different formats."""
    if not tp or not Context._traceparent_valid(tp):
        return None
    parts = tp.split("-")
    return parts[1], parts[2]


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_span_id: Optional[str] = None
    start: float = 0.0          # epoch seconds (cross-process stitchable)
    end: Optional[float] = None
    service: str = ""
    request_id: Optional[str] = None
    attributes: dict[str, Any] = field(default_factory=dict)
    status: str = "ok"

    @property
    def duration(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start

    def set(self, **attrs) -> "Span":
        self.attributes.update(attrs)
        return self

    def to_dict(self) -> dict:
        return {
            "name": self.name, "trace_id": self.trace_id,
            "span_id": self.span_id, "parent_span_id": self.parent_span_id,
            "start": self.start, "end": self.end, "service": self.service,
            "request_id": self.request_id, "attributes": self.attributes,
            "status": self.status,
        }

    @staticmethod
    def from_dict(d: dict) -> "Span":
        return Span(
            name=d.get("name", ""), trace_id=d.get("trace_id", ""),
            span_id=d.get("span_id", ""),
            parent_span_id=d.get("parent_span_id"),
            start=d.get("start", 0.0), end=d.get("end"),
            service=d.get("service", ""), request_id=d.get("request_id"),
            attributes=d.get("attributes") or {},
            status=d.get("status", "ok"),
        )


class _NoopSpan:
    """Returned when the context has no trace identity: every method a real
    span exposes, doing nothing — call sites stay guard-free."""

    name = trace_id = span_id = service = ""
    parent_span_id = request_id = end = duration = None
    start = 0.0
    status = "ok"
    attributes: dict = {}

    def set(self, **attrs):
        return self

    def __setattr__(self, k, v):  # the singleton must stay immutable
        pass


_NOOP = _NoopSpan()


class _SpanScope:
    """Context manager from ``Tracer.span``: starts on enter, binds
    CURRENT_SPAN, ends + records on exit (status=error on exception)."""

    def __init__(self, tracer: "Tracer", name: str, ctx, service, attrs,
                 adopt_wire_span: bool = False):
        self._tracer = tracer
        self._name = name
        self._ctx = ctx
        self._service = service
        self._attrs = attrs
        self._adopt = adopt_wire_span
        self._span = _NOOP
        self._token = None

    def __enter__(self):
        self._span = self._tracer.start(self._name, self._ctx,
                                        service=self._service,
                                        adopt_wire_span=self._adopt,
                                        **self._attrs)
        if self._span is not _NOOP:
            self._token = CURRENT_SPAN.set(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb):
        if self._token is not None:
            CURRENT_SPAN.reset(self._token)
        if self._span is not _NOOP:
            if exc_type is not None:
                self._span.status = "error"
                self._span.set(error=repr(exc)[:200])
            self._tracer.finish(self._span)
        return False


class Tracer:
    """Bounded in-process trace store + SLO histogram feeder.

    One per process (``get_tracer()``); thread-safe — spans may end from
    worker threads (the engine's sampling thread) while the event loop
    starts new ones.
    """

    def __init__(self, service: str = "", capacity: int = 2048,
                 metrics: Optional[MetricsRegistry] = None):
        self.service = service or os.environ.get("DYN_SERVICE", "dynamo")
        self.metrics = metrics or MetricsRegistry()
        self._spans: collections.deque[Span] = collections.deque(
            maxlen=capacity)
        self._lock = threading.Lock()
        self._jsonl_path = os.environ.get("DYN_TRACE_JSONL") or None
        # pre-create the SLO series so /metrics exposes them before the
        # first request (operators wire dashboards against empty series)
        self.metrics.histogram(
            "phase_seconds", "Per-phase request latency by span name")
        self.metrics.histogram(
            "ttft_seconds", "Time to first streamed token (frontend)")
        self.metrics.histogram(
            "itl_seconds", "Inter-token latency (frontend, per gap)")
        self.metrics.histogram(
            "e2e_seconds", "End-to-end request latency (frontend)")
        self.metrics.histogram(
            "engine_ttft_seconds",
            "Engine-side queue+prefill time to first token")

    # ------------------------------------------------------------ creation

    @staticmethod
    def _resolve_ctx(ctx):
        """A usable Context (has a trace identity) or None. ``ctx=None``
        falls back to the task-local CURRENT_REQUEST — worker-side helpers
        (e.g. the KV transfer manager) have no ctx parameter but run under
        the endpoint pump which binds it."""
        if ctx is None:
            ctx = CURRENT_REQUEST.get()
        if ctx is None or not hasattr(ctx, "ensure_traceparent"):
            return None
        return ctx

    def start(self, name: str, ctx=None, service: Optional[str] = None,
              adopt_wire_span: bool = False, **attrs) -> Span:
        """``adopt_wire_span``: the span takes the traceparent's own span id
        as its identity instead of parenting to it — for the trust-boundary
        root when the frontend SYNTHESIZED the traceparent (a parent id that
        no process ever recorded would read as a broken chain)."""
        ctx = self._resolve_ctx(ctx)
        if ctx is None:
            return _NOOP
        parsed = parse_traceparent(ctx.ensure_traceparent())
        if parsed is None:
            return _NOOP
        trace_id, wire_span = parsed
        if not trace_sampled(trace_id):
            return _NOOP  # head-sampled out: no span, no histogram feed
        cur = CURRENT_SPAN.get()
        if cur is not None and cur.trace_id == trace_id:
            parent, span_id = cur.span_id, secrets.token_hex(8)
        elif adopt_wire_span:
            parent, span_id = None, wire_span
        else:
            parent, span_id = wire_span, secrets.token_hex(8)
        return Span(
            name=name, trace_id=trace_id, span_id=span_id,
            parent_span_id=parent, start=time.time(),
            service=service or self.service,
            request_id=getattr(ctx, "id", None), attributes=dict(attrs))

    def finish(self, span: Span) -> None:
        if span is _NOOP or isinstance(span, _NoopSpan):
            return
        if span.end is None:
            span.end = time.time()
        self._store(span)

    def span(self, name: str, ctx=None, service: Optional[str] = None,
             adopt_wire_span: bool = False, **attrs) -> _SpanScope:
        """``with tracer.span("router.schedule", ctx) as sp: ...``"""
        return _SpanScope(self, name, ctx, service, attrs,
                          adopt_wire_span=adopt_wire_span)

    def record(self, name: str, ctx=None, start: Optional[float] = None,
               end: Optional[float] = None, service: Optional[str] = None,
               **attrs) -> Span:
        """Record a span retroactively from measured timestamps (epoch
        seconds) — how TTFT/ITL phases are logged once the boundary token
        has actually been observed."""
        sp = self.start(name, ctx, service=service, **attrs)
        if sp is _NOOP or isinstance(sp, _NoopSpan):
            return sp
        now = time.time()
        sp.start = start if start is not None else now
        sp.end = end if end is not None else now
        self._store(sp)
        return sp

    def record_hop(self, ctx, hop_traceparent: Optional[str],
                   **attrs) -> Span:
        """Record the wire hop minted by ``Context.to_wire`` as a real span
        (name ``rpc.send``) so the receiver's spans — which parent to that
        hop id — stitch back to the sender's chain."""
        parsed = parse_traceparent(hop_traceparent)
        if parsed is None:
            return _NOOP
        trace_id, hop_span = parsed
        if not trace_sampled(trace_id):
            return _NOOP
        cur = CURRENT_SPAN.get()
        parent = None
        if cur is not None and cur.trace_id == trace_id:
            parent = cur.span_id
        else:
            own = parse_traceparent(getattr(ctx, "traceparent", None))
            if own is not None and own[0] == trace_id:
                parent = own[1]
        now = time.time()
        sp = Span(name="rpc.send", trace_id=trace_id, span_id=hop_span,
                  parent_span_id=parent, start=now, end=now,
                  service=self.service,
                  request_id=getattr(ctx, "id", None),
                  attributes=dict(attrs))
        self._store(sp)
        return sp

    # ------------------------------------------------------------- storage

    def _store(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
        dur = span.duration
        if dur is not None and dur >= 0 and span.name not in _NO_HISTOGRAM:
            self.metrics.histogram("phase_seconds").observe(
                dur, phase=span.name)
            extra = _SLO_HISTOGRAMS.get(span.name)
            if extra:
                self.metrics.histogram(extra).observe(dur)
        path = self._jsonl_path
        if path:
            try:
                with open(path, "a") as f:
                    f.write(json.dumps(span.to_dict()) + "\n")
            except OSError:
                self._jsonl_path = None  # never retry a broken sink per span

    def spans_for(self, request_or_trace_id: str) -> list[Span]:
        """All buffered spans whose request id OR trace id matches, oldest
        first (the request id doubles as the trace id when the client sent
        no traceparent — context.py:ensure_traceparent)."""
        rid = request_or_trace_id
        with self._lock:
            return [s for s in self._spans
                    if s.request_id == rid or s.trace_id == rid]

    def all_spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def export_jsonl(self, path: str) -> int:
        """Dump every buffered span as JSONL; returns the line count."""
        spans = self.all_spans()
        with open(path, "w") as f:
            for s in spans:
                f.write(json.dumps(s.to_dict()) + "\n")
        return len(spans)


# ---------------------------------------------------------------- singleton

_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    """The process-global tracer (created on first use)."""
    global _tracer
    if _tracer is None:
        with _tracer_lock:
            if _tracer is None:
                _tracer = Tracer()
    return _tracer


def configure_tracer(service: Optional[str] = None,
                     capacity: Optional[int] = None) -> Tracer:
    """Re-create the global tracer (entrypoints name their role; tests
    isolate their buffers)."""
    global _tracer
    with _tracer_lock:
        _tracer = Tracer(service=service or "",
                         capacity=capacity or 2048)
    return _tracer


def stitch(spans: list[dict]) -> list[dict]:
    """Order raw span dicts into a parent-first tree walk with a ``depth``
    key added — shared by ``dynctl trace`` and anything rendering a trace.
    Orphans (parent not in the set) surface as roots, not silently dropped."""
    by_id = {s["span_id"]: s for s in spans}
    children: dict[Optional[str], list[dict]] = {}
    for s in spans:
        parent = s.get("parent_span_id")
        key = parent if parent in by_id and parent != s["span_id"] else None
        children.setdefault(key, []).append(s)
    for sibs in children.values():
        sibs.sort(key=lambda s: (s.get("start") or 0.0))
    out: list[dict] = []
    seen: set[str] = set()

    def walk(s: dict, depth: int) -> None:
        if s["span_id"] in seen:
            return
        seen.add(s["span_id"])
        out.append({**s, "depth": depth})
        for c in children.get(s["span_id"], []):
            walk(c, depth + 1)

    for root in children.get(None, []):
        walk(root, 0)
    for s in spans:  # cycles / self-parents: still emitted
        walk(s, 0)
    return out
