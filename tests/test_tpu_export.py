"""TPU cross-lowering guard: the Pallas kernels must export for the TPU
target from any host.

``jax.export(platforms=["tpu"])`` runs Pallas→Mosaic MLIR generation and
the Mosaic dialect verifier WITHOUT a TPU — catching unsupported kernel
constructs (bad BlockSpecs, illegal slicing, layout violations) at CI
time instead of burning a scarce chip window on them (the r5 situation:
the transposed VMEM scale layout and its dynamic lane slicing shipped
with the tunnel down all round). The deeper Mosaic→LLO compile still
happens on-device, so this is necessary-not-sufficient — but every
failure it CAN catch is one the chip never has to.
"""

from unittest import mock

import jax
import jax.numpy as jnp


def _export_tpu(fn, *args):
    # paged_attention picks interpret mode off the default backend; fake
    # a TPU host so the REAL kernel path lowers (the export target is
    # what matters, not the local backend)
    with mock.patch.object(jax, "default_backend", return_value="tpu"):
        exp = jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)
    assert b"tpu_custom_call" in exp.mlir_module_serialized, \
        "no Mosaic kernel in the exported module (interpret path lowered?)"
    return exp


def test_gqa_decode_kernel_exports_for_tpu():
    from dynamo_tpu.ops.paged_attention import paged_attention_decode

    B, KV, hd, H, bs, nb = 4, 8, 128, 32, 16, 32
    slots = nb * bs
    q = jnp.zeros((B, H, hd), jnp.bfloat16)
    kc = jnp.zeros((slots, KV, hd), jnp.bfloat16)
    bt = jnp.zeros((B, nb), jnp.int32)
    lens = jnp.full((B,), 64, jnp.int32)

    _export_tpu(lambda *a: paged_attention_decode(*a, block_size=bs),
                q, kc, kc, bt, lens)


def test_gqa_decode_int8_scale_placements_export_for_tpu(monkeypatch):
    """Both int8 scale placements: VMEM-resident transposed [KV, slots]
    (incl. the scale_slot_base rebase + dynamic lane slice) and the
    per-page scale-DMA fallback."""
    from dynamo_tpu.ops.paged_attention import paged_attention_decode

    B, KV, hd, H, bs, nb = 4, 8, 128, 32, 16, 32
    slots = nb * bs
    q = jnp.zeros((B, H, hd), jnp.bfloat16)
    kc = jnp.zeros((slots, KV, hd), jnp.int8)
    bt = jnp.zeros((B, nb), jnp.int32)
    lens = jnp.full((B,), 64, jnp.int32)
    ks = jnp.ones((slots, KV), jnp.float32)

    def make_fn():
        # a FRESH function object per export: the env var is read at trace
        # time, and jax's trace cache is keyed on (callable, avals) — the
        # same object would silently reuse the first placement's jaxpr
        def fn(*a):
            q, kc, vc, bt, lens, ks, vs = a
            return paged_attention_decode(q, kc, vc, bt, lens,
                                          block_size=bs, k_scales=ks,
                                          v_scales=vs,
                                          scale_slot_base=slots)
        return fn

    monkeypatch.setenv("DYN_KV_SCALE_VMEM_BYTES", str(1 << 30))
    _export_tpu(make_fn(), q, kc, kc, bt, lens, ks, ks)
    monkeypatch.setenv("DYN_KV_SCALE_VMEM_BYTES", "0")
    _export_tpu(make_fn(), q, kc, kc, bt, lens, ks, ks)


def test_mla_decode_kernels_export_for_tpu():
    from dynamo_tpu.ops.paged_attention import mla_paged_decode

    B, H, R, PR, bs, nb = 4, 16, 512, 128, 16, 32
    slots = nb * bs
    qe = jnp.zeros((B, H, R), jnp.bfloat16)
    qr = jnp.zeros((B, H, PR), jnp.bfloat16)
    bt = jnp.zeros((B, nb), jnp.int32)
    lens = jnp.full((B,), 64, jnp.int32)

    _export_tpu(lambda *a: mla_paged_decode(
        *a, block_size=bs, scale=0.1),
        qe, qr, jnp.zeros((slots, R), jnp.bfloat16),
        jnp.zeros((slots, PR), jnp.bfloat16), bt, lens)

    # int8 latent pages with lane-packed scales + slot-base rebase
    _export_tpu(lambda qe, qr, cc, rc, bt, lens, cs, rs: mla_paged_decode(
        qe, qr, cc, rc, bt, lens, block_size=bs, scale=0.1,
        c_scales=cs, r_scales=rs, scale_slot_base=slots),
        qe, qr, jnp.zeros((slots, R), jnp.int8),
        jnp.zeros((slots, PR), jnp.int8), bt, lens,
        jnp.ones((slots,), jnp.float32), jnp.ones((slots,), jnp.float32))


def test_flash_prefill_kernel_exports_for_tpu():
    from dynamo_tpu.ops.flash_prefill import flash_prefill_paged

    L, KV, hd, H, bs, nb, B, S = 2, 8, 128, 32, 16, 16, 2, 64
    slots = nb * bs
    q = jnp.zeros((B, S, H, hd), jnp.bfloat16)
    kc = jnp.zeros((L, slots, KV, hd), jnp.bfloat16)
    lidx = jnp.int32(0)
    bt = jnp.zeros((B, nb), jnp.int32)
    pos = jnp.tile(jnp.arange(S, dtype=jnp.int32), (B, 1))
    lens = jnp.full((B,), S, jnp.int32)

    _export_tpu(lambda *a: flash_prefill_paged(*a, block_size=bs),
                q, kc, kc, lidx, bt, pos, lens)


def test_full_serving_step_exports_for_tpu():
    """The COMPOSED serving step — scan over layers, Pallas decode
    attention, int8 resident weights, int8 KV with layer-sliced scales —
    at llama3-1b production widths (depth-reduced: scan makes the
    program identical modulo the leading L dim)."""
    import functools

    import numpy as np

    from dynamo_tpu.engine import model as M
    from dynamo_tpu.engine.cache import allocate_device_cache
    from dynamo_tpu.engine.config import ModelConfig
    from dynamo_tpu.engine.quant import quantize_params

    cfg = ModelConfig(
        vocab_size=128256, hidden_size=2048, intermediate_size=8192,
        num_layers=2, num_heads=32, num_kv_heads=8, head_dim=64,
        rope_theta=500000.0, max_position_embeddings=8192,
        tie_word_embeddings=True)
    bs, nb, B, W = 16, 64, 8, 16
    params = quantize_params(
        jax.tree.map(np.asarray, M.init_params(cfg, jax.random.key(0))),
        "int8")
    kc, vc = allocate_device_cache(cfg, nb, bs, None, dtype="int8")
    args = (params,
            jnp.zeros((B, 1), jnp.int32), jnp.zeros((B, 1), jnp.int32),
            jnp.zeros((B, 1), jnp.int32),
            jnp.zeros((B, W), jnp.int32), jnp.full((B,), 64, jnp.int32),
            jnp.zeros((B,), jnp.int32), kc, vc)
    fn = functools.partial(M.forward, cfg=cfg, block_size=bs,
                           use_pallas=True)
    _export_tpu(fn, *args)


def test_flash_prefill_int8_cache_exports_for_tpu():
    """Quant-cache flash prefill ({"q","s"} pytree caches, dequant fused
    into the page gather) must also cross-lower for TPU."""
    from dynamo_tpu.ops.flash_prefill import flash_prefill_paged

    L, KV, hd, H, bs, nb, B, S = 2, 8, 128, 32, 16, 16, 2, 64
    slots = nb * bs
    q = jnp.zeros((B, S, H, hd), jnp.bfloat16)
    kq = {"q": jnp.zeros((L, slots, KV, hd), jnp.int8),
          "s": jnp.ones((L, slots, KV), jnp.float32)}
    lidx = jnp.int32(0)
    bt = jnp.zeros((B, nb), jnp.int32)
    pos = jnp.tile(jnp.arange(S, dtype=jnp.int32), (B, 1))
    lens = jnp.full((B,), S, jnp.int32)

    _export_tpu(lambda *a: flash_prefill_paged(*a, block_size=bs),
                q, kq, kq, lidx, bt, pos, lens)
