"""Performance analysis tooling (ref: lib/llm/src/perf.rs, perf/logprobs.rs)."""

from dynamo_tpu.perf.logprobs import (  # noqa: F401
    ChoiceAnalysis,
    SensitivityAnalysis,
    analyze_logprob_sensitivity,
    compare_runs,
)
from dynamo_tpu.perf.recording import (  # noqa: F401
    LatencySummary,
    RecordedStream,
    StreamRecorder,
    TimestampedResponse,
    record_stream,
    summarize,
)
