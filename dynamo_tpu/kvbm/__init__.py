"""KVBM — multi-tier KV block manager.

Rebuild of the reference's block manager (ref: lib/llm/src/block_manager.rs:
62-75 — CacheLevel G1 device / G2 host / G3 disk / G4 remote; offload on
registration, onboard on cache miss, ref: block_manager/offload.rs:4-34).

TPU mapping: G1 is the engine's paged HBM cache (engine/cache.py BlockPool);
G2 is TPU-VM host DRAM (generous on TPU-VMs — it doubles as the disagg
staging buffer); G3 is local NVMe. Transfers ride ops/block_copy
gather/scatter (one DMA per bundle) instead of CUDA copy streams; there is
no NIXL — cross-host movement goes through the response plane (disagg) or
the object store.
"""

from dynamo_tpu.kvbm.tiers import DiskTier, HostTier
from dynamo_tpu.kvbm.manager import KvbmManager

__all__ = ["DiskTier", "HostTier", "KvbmManager"]
