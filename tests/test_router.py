"""KV router: radix indexer, scheduler cost function, event flow, push router."""

import asyncio
import random

import pytest

from dynamo_tpu.router import (
    ActiveSequences,
    ApproxKvIndexer,
    KvEventPublisher,
    KvIndexer,
    KvRouter,
    RadixTree,
    KvScheduler,
    softmax_sample,
)
from dynamo_tpu.router.protocols import KvCacheEvent, KvRouterConfig, RouterEvent, StoredBlock
from dynamo_tpu.router.scheduler import NoWorkersError
from dynamo_tpu.runtime.control_plane import LocalControlPlane
from dynamo_tpu.tokens import compute_block_hash_for_seq, compute_seq_hash_for_block

pytestmark = pytest.mark.anyio

W0, W1 = 100, 200


def stored_event(worker, tokens, block_size=4, event_id=1, parent=None):
    local = compute_block_hash_for_seq(tokens, block_size)
    ext = compute_seq_hash_for_block(local)
    blocks = [StoredBlock(e, l) for e, l in zip(ext, local)]
    return RouterEvent(worker, KvCacheEvent.stored(event_id, parent, blocks)), local, ext


def test_radix_tree_overlap_scores():
    tree = RadixTree()
    toks = list(range(16))
    ev0, local, _ = stored_event(W0, toks)
    tree.apply_event(ev0)
    ev1, _, _ = stored_event(W1, toks[:8])
    tree.apply_event(ev1)

    scores = tree.find_matches(local).scores
    assert scores == {W0: 4, W1: 2}

    # divergent suffix matches only the shared prefix
    other = toks[:8] + [99, 98, 97, 96]
    scores = tree.find_matches(compute_block_hash_for_seq(other, 4)).scores
    assert scores == {W0: 2, W1: 2}

    # unrelated tokens match nothing
    assert tree.find_matches(compute_block_hash_for_seq(list(range(50, 66)), 4)).scores == {}


def test_radix_tree_removal_and_clear():
    tree = RadixTree()
    toks = list(range(16))
    ev0, local, ext0 = stored_event(W0, toks)
    tree.apply_event(ev0)
    ev1, _, _ = stored_event(W1, toks)
    tree.apply_event(ev1)

    # remove W0's last two blocks
    tree.apply_event(RouterEvent(W0, KvCacheEvent.removed(2, ext0[2:])))
    scores = tree.find_matches(local).scores
    assert scores == {W0: 2, W1: 4}

    tree.remove_worker(W1)
    scores = tree.find_matches(local).scores
    assert scores == {W0: 2}


def test_radix_tree_dump_load_roundtrip():
    tree = RadixTree()
    ev0, local, ext = stored_event(W0, list(range(16)))
    tree.apply_event(ev0)
    restored = RadixTree.load(tree.dump())
    assert restored.find_matches(local).scores == {W0: 4}
    # removal by external hash still works after restore
    restored.apply_event(RouterEvent(W0, KvCacheEvent.removed(2, ext[3:])))
    assert restored.find_matches(local).scores == {W0: 3}


def test_softmax_sample_argmin_at_zero_temperature():
    rng = random.Random(0)
    logits = {1: 5.0, 2: 1.0, 3: 9.0}
    assert all(softmax_sample(logits, 0.0, rng) == 2 for _ in range(10))


def test_softmax_sample_temperature_spreads():
    rng = random.Random(0)
    logits = {1: 1.0, 2: 1.5}
    picks = {softmax_sample(logits, 1.0, rng) for _ in range(200)}
    assert picks == {1, 2}


def test_scheduler_prefers_overlap_and_balances_load():
    from dynamo_tpu.router.indexer import OverlapScores

    sched = KvScheduler(block_size=4, config=KvRouterConfig())
    # W0 has 3 blocks of overlap, W1 none → W0 wins
    d = sched.schedule(
        "r1", isl_tokens=16, seq_hashes=[11, 12, 13, 14],
        overlaps=OverlapScores(scores={W0: 3}), worker_ids=[W0, W1],
    )
    assert d.worker_id == W0
    assert d.overlap_blocks == 3

    # now W0 is loaded with r1's 4 blocks + 4 prefill tokens; a fresh request
    # with no overlap anywhere goes to the idle W1
    d2 = sched.schedule(
        "r2", isl_tokens=16, seq_hashes=[21, 22, 23, 24],
        overlaps=OverlapScores(), worker_ids=[W0, W1],
    )
    assert d2.worker_id == W1

    sched.free("r1")
    sched.free("r2")


def test_scheduler_no_workers():
    from dynamo_tpu.router.indexer import OverlapScores

    sched = KvScheduler(block_size=4)
    with pytest.raises(NoWorkersError):
        sched.schedule("r", 16, None, OverlapScores(), [])


def test_active_sequences_shared_blocks_counted_once():
    seqs = ActiveSequences(block_size=4)
    seqs.add_request("a", [1, 2, 3], isl=12, overlap=0)
    seqs.add_request("b", [1, 2, 9], isl=12, overlap=1)
    assert seqs.active_blocks == 4  # {1,2,3,9}
    assert seqs.active_tokens == 12 + 8
    seqs.mark_prefill_completed("a")
    assert seqs.active_tokens == 8
    seqs.free("b")
    assert seqs.active_blocks == 3
    seqs.free("a")
    assert seqs.active_blocks == 0


async def test_indexer_event_flow_via_stream():
    plane = LocalControlPlane()
    pub = KvEventPublisher(plane, worker_id=W0, kv_block_size=4)
    indexer = await KvIndexer(plane, kv_block_size=4).start()

    toks = list(range(16))
    local = compute_block_hash_for_seq(toks, 4)
    ext = compute_seq_hash_for_block(local)
    await pub.publish_stored(None, [StoredBlock(e, l) for e, l in zip(ext, local)])
    for _ in range(100):
        if indexer.events_applied:
            break
        await asyncio.sleep(0.01)
    assert indexer.find_matches_for_tokens(toks).scores == {W0: 4}

    await pub.publish_removed(ext[2:])
    for _ in range(100):
        if indexer.events_applied == 2:
            break
        await asyncio.sleep(0.01)
    assert indexer.find_matches_for_tokens(toks).scores == {W0: 2}
    await indexer.stop()
    await plane.close()


def test_approx_indexer_ttl():
    idx = ApproxKvIndexer(kv_block_size=4, ttl=0.0)  # instant expiry
    toks = list(range(8))
    idx.process_routing_decision_for_request(toks, W0)
    assert idx.find_matches_for_tokens(toks).scores == {}

    idx2 = ApproxKvIndexer(kv_block_size=4, ttl=60.0)
    idx2.process_routing_decision_for_request(toks, W0)
    assert idx2.find_matches_for_tokens(toks).scores == {W0: 2}


async def test_kv_router_end_to_end_routing():
    plane = LocalControlPlane()
    router = await KvRouter(plane, block_size=4).start()
    pub = KvEventPublisher(plane, worker_id=W0, kv_block_size=4)

    toks = list(range(16))
    local = compute_block_hash_for_seq(toks, 4)
    ext = compute_seq_hash_for_block(local)
    await pub.publish_stored(None, [StoredBlock(e, l) for e, l in zip(ext, local)])
    for _ in range(100):
        if router.indexer.events_applied:
            break
        await asyncio.sleep(0.01)

    d = router.find_best_match("req1", toks, [W0, W1])
    assert d.worker_id == W0 and d.overlap_blocks == 4
    router.mark_prefill_completed("req1")
    router.free("req1")
    await router.stop()
    await plane.close()


async def test_indexer_snapshot_write_and_restore():
    """Durability (r1 verdict item #6): the tree is snapshotted to the
    object store every N events and a restarted router restores it even
    when the event stream no longer replays the early events."""
    from dynamo_tpu.router.indexer import KvIndexer, RADIX_BUCKET
    from dynamo_tpu.router.publisher import KvEventPublisher

    plane = LocalControlPlane()
    idx = await KvIndexer(plane, kv_block_size=4,
                          snapshot_threshold=3).start()
    pub = KvEventPublisher(plane, worker_id=W0, kv_block_size=4)

    toks = list(range(16))
    local = compute_block_hash_for_seq(toks, 4)
    ext = compute_seq_hash_for_block(local)
    for i in range(4):  # 4 chained events > threshold 3
        await pub.publish_stored(
            ext[i - 1] if i else None, [StoredBlock(ext[i], local[i])])
    for _ in range(200):
        if idx.snapshots_written:
            break
        await asyncio.sleep(0.01)
    assert idx.snapshots_written >= 1
    assert await plane.object_get(RADIX_BUCKET, idx.stream) is not None
    # snapshot lock was released (lease revoked deletes the key)
    assert await plane.kv_get(f"locks/radix/{idx.stream}") is None
    await idx.stop()

    # "restarted frontend": consume NOTHING from the stream (start beyond
    # its end) — any overlap must come from the restored snapshot
    last = await plane.stream_last_seq(idx.stream)
    idx2 = await KvIndexer(plane, kv_block_size=4,
                           snapshot_threshold=3).start(start_seq=last + 1)
    # the first chain of blocks present at snapshot time must match; the
    # snapshot covered at least threshold (3) of the 4 events
    scores = idx2.find_matches(local)
    assert scores.scores.get(W0, 0) >= 3
    await idx2.stop()

    # router_reset_states ignores the snapshot
    idx3 = await KvIndexer(plane, kv_block_size=4, snapshot_threshold=3,
                           reset_states=True).start(start_seq=last + 1)
    assert idx3.find_matches(local).scores == {}
    await idx3.stop()
    await plane.close()


async def test_router_replica_sync_load_propagates():
    """Two router replicas with router_replica_sync: a decision on A shows
    up in B's active-sequence load (and clears on free)."""
    cfg = KvRouterConfig(use_kv_events=False, router_replica_sync=True)
    plane = LocalControlPlane()
    a = await KvRouter(plane, block_size=4, config=cfg).start()
    b = await KvRouter(plane, block_size=4, config=cfg).start()

    toks = list(range(32))
    d = a.find_best_match("sync-req", toks, [W0, W1])
    for _ in range(200):
        if b.scheduler.slots.active_load().get(d.worker_id, (0, 0))[0]:
            break
        await asyncio.sleep(0.01)
    blocks, tokens = b.scheduler.slots.active_load()[d.worker_id]
    assert blocks == 8 and tokens == 32

    a.mark_prefill_completed("sync-req")
    a.free("sync-req")
    for _ in range(200):
        if b.scheduler.slots.active_load().get(d.worker_id, (1, 1)) == (0, 0):
            break
        await asyncio.sleep(0.01)
    assert b.scheduler.slots.active_load()[d.worker_id] == (0, 0)
    await a.stop()
    await b.stop()
    await plane.close()
