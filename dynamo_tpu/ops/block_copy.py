"""Paged-KV block gather/scatter (the reference CUDA kernel's TPU analog).

The reference ships one CUDA kernel — a dimension-aware strided block copy
used for KV transfer and (de)fragmentation (ref: lib/llm/src/kernels/
block_copy.cu:40-758). On TPU the same jobs are XLA dynamic gathers/scatters
over the flat paged cache: XLA already emits single-pass DMA programs for
these, so the kernels below are thin, jit-friendly contracts used by the
KVBM offload path (device→host staging) and disagg KV transfer:

  gather_blocks:  cache [L, slots, KV, hd] + ids [n] → [L, n, bs, KV, hd]
  scatter_blocks: writes such a bundle back into (possibly different) slots

A layout transpose between prefill-TP and decode-TP shardings is the
``reshard`` helper: gather → logical reshape → device_put under the target
sharding (XLA inserts the all-to-all).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _pad_pow2_ids(block_ids: np.ndarray) -> np.ndarray:
    """Pad an id list to the next power of two by repeating the last id —
    duplicate gathers/scatters of the same block are idempotent, and the
    bounded shape set keeps the XLA compile cache from growing per prompt
    length (the engine pads all other shapes the same way)."""
    n = len(block_ids)
    p = 1
    while p < n:
        p *= 2
    if p == n:
        return block_ids
    return np.concatenate([block_ids, np.repeat(block_ids[-1:], p - n)])


def gather_blocks(cache, block_ids, *, block_size: int) -> jax.Array:
    """Pull whole blocks out of the flat paged cache.

    cache: [L, num_slots, KV, hd] array → bundle [L, P, block_size, KV, hd];
    int8 {"q","s"} cache → PACKED uint8 bundle [L, P, bs·KV·(hd+4)] (native
    (q, s) bytes — engine/cache.pack_kv_blocks). P = next pow2 ≥ n (trailing
    entries repeat the last block; slice axis 1 host-side for exact n).

    Packed bundles keep KVBM tiers and the disagg wire at ~1 byte/element
    (4x smaller than an f32 bundle, 2x smaller than bf16) and make the
    offload→onboard roundtrip bit-exact by construction — the packing
    happens on device, so the device→host copy shrinks identically."""
    from dynamo_tpu.engine.cache import is_quant_cache, pack_kv_blocks

    if is_quant_cache(cache):
        L, slots, KV, hd = cache["q"].shape
        ids = jnp.asarray(_pad_pow2_ids(np.asarray(block_ids, np.int32)))
        qp = cache["q"].reshape(L, slots // block_size, block_size, KV, hd)
        sp = cache["s"].reshape(L, slots // block_size, block_size, KV)
        return pack_kv_blocks(jnp.take(qp, ids, axis=1),
                              jnp.take(sp, ids, axis=1))
    L, slots, KV, hd = cache.shape
    ids = _pad_pow2_ids(np.asarray(block_ids, np.int32))
    paged = cache.reshape(L, slots // block_size, block_size, KV, hd)
    return jnp.take(paged, jnp.asarray(ids), axis=1)


import functools


@functools.partial(jax.jit, static_argnames=("block_size",), donate_argnums=(0,))
def _scatter(cache, block_ids, bundle, *, block_size):
    L, slots, KV, hd = cache.shape
    paged = cache.reshape(L, slots // block_size, block_size, KV, hd)
    return paged.at[:, block_ids].set(bundle).reshape(L, slots, KV, hd)


@functools.partial(jax.jit, static_argnames=("block_size",), donate_argnums=(0,))
def _scatter_quant(cache, block_ids, bundle, *, block_size):
    """Quantize the f32 bundle in-trace and write both cache leaves."""
    from dynamo_tpu.engine.cache import quantize_kv

    L, slots, KV, hd = cache["q"].shape
    qb, sb = quantize_kv(bundle)  # [L, n, bs, KV, hd] / [L, n, bs, KV]
    qp = cache["q"].reshape(L, slots // block_size, block_size, KV, hd)
    sp = cache["s"].reshape(L, slots // block_size, block_size, KV)
    return {
        "q": qp.at[:, block_ids].set(qb).reshape(L, slots, KV, hd),
        "s": sp.at[:, block_ids].set(sb).reshape(L, slots, KV),
    }


@functools.partial(jax.jit, static_argnames=("block_size",), donate_argnums=(0,))
def _scatter_packed(cache, block_ids, bundle, *, block_size):
    """Write a packed uint8 bundle's (q, s) bytes straight into the cache
    leaves — no requant, bit-exact by construction."""
    from dynamo_tpu.engine.cache import unpack_kv_blocks

    L, slots, KV, hd = cache["q"].shape
    qb, sb = unpack_kv_blocks(bundle, block_size, KV, hd)
    qp = cache["q"].reshape(L, slots // block_size, block_size, KV, hd)
    sp = cache["s"].reshape(L, slots // block_size, block_size, KV)
    return {
        "q": qp.at[:, block_ids].set(qb).reshape(L, slots, KV, hd),
        "s": sp.at[:, block_ids].set(sb).reshape(L, slots, KV),
    }


@functools.partial(jax.jit, static_argnames=("block_size", "start_layer"),
                   donate_argnums=(0,))
def _scatter_layers(cache, block_ids, bundle, *, block_size, start_layer):
    """Write a LAYER SLICE [nL, n, bs, KV, hd] of a bundle into layers
    [start_layer, start_layer+nL) of the cache. start_layer is static: the
    prefill side splits into a fixed group count, so the signature set is
    bounded by groups × widths (same discipline as the pow2 id padding)."""
    L, slots, KV, hd = cache.shape
    nL = bundle.shape[0]
    paged = cache.reshape(L, slots // block_size, block_size, KV, hd)
    return (paged.at[start_layer:start_layer + nL, block_ids]
            .set(bundle).reshape(L, slots, KV, hd))


@functools.partial(jax.jit, static_argnames=("block_size", "start_layer"),
                   donate_argnums=(0,))
def _scatter_packed_layers(cache, block_ids, bundle, *, block_size,
                           start_layer):
    """Layer-sliced write of a packed uint8 [nL, n, X] quant bundle."""
    from dynamo_tpu.engine.cache import unpack_kv_blocks

    L, slots, KV, hd = cache["q"].shape
    nL = bundle.shape[0]
    qb, sb = unpack_kv_blocks(bundle, block_size, KV, hd)
    qp = cache["q"].reshape(L, slots // block_size, block_size, KV, hd)
    sp = cache["s"].reshape(L, slots // block_size, block_size, KV)
    return {
        "q": (qp.at[start_layer:start_layer + nL, block_ids]
              .set(qb).reshape(L, slots, KV, hd)),
        "s": (sp.at[start_layer:start_layer + nL, block_ids]
              .set(sb).reshape(L, slots, KV)),
    }


@functools.partial(jax.jit, static_argnames=("block_size", "start_layer"),
                   donate_argnums=(0,))
def _scatter_quant_layers(cache, block_ids, bundle, *, block_size,
                          start_layer):
    """Layer-sliced write of a VALUE bundle into an int8 cache (quantize
    in-trace — the cross-layout pair of _scatter_quant)."""
    from dynamo_tpu.engine.cache import quantize_kv

    L, slots, KV, hd = cache["q"].shape
    nL = bundle.shape[0]
    qb, sb = quantize_kv(bundle)
    qp = cache["q"].reshape(L, slots // block_size, block_size, KV, hd)
    sp = cache["s"].reshape(L, slots // block_size, block_size, KV)
    return {
        "q": (qp.at[start_layer:start_layer + nL, block_ids]
              .set(qb).reshape(L, slots, KV, hd)),
        "s": (sp.at[start_layer:start_layer + nL, block_ids]
              .set(sb).reshape(L, slots, KV)),
    }


def _is_packed(bundle) -> bool:
    # attribute check, not np.asarray: device bundles must not round-trip
    # through host memory just to inspect dtype
    return (getattr(bundle, "dtype", None) == np.uint8
            and getattr(bundle, "ndim", 0) == 3)


def scatter_blocks(cache, block_ids, bundle, *, block_size: int,
                   start_layer=None):
    """Write a gathered bundle into blocks of the cache; returns new cache.

    bundle: [L, n, bs, KV, hd] values (np or jax), or a packed uint8
    [L, n, X] quant bundle (gather_blocks' native int8-cache format). The
    flat cache is donated at the jit boundary (reshapes live inside it), so
    the write is in-place in HBM — no transient second cache. ids/bundle
    are pow2-padded (idempotent duplicate writes) to bound the compile
    cache.

    Cross-layout pairs both work: a packed bundle into a plain cache
    dequantizes on the way in (mixed prefill/decode deployments); a value
    bundle into an int8 cache re-quantizes in-trace (bit-exact for bundles
    that started as quantized pages — engine/cache.py int8 notes).

    ``start_layer`` (int) means the bundle is a LAYER SLICE: its leading
    axis covers only layers [start_layer, start_layer + nL) of the cache —
    the layer-interleaved disagg transfer path (docs/disagg.md). None =
    full depth.
    """
    from dynamo_tpu.engine.cache import (
        is_quant_cache, unpack_kv_blocks, dequantize_kv,
    )

    ids = np.asarray(block_ids, np.int32)
    pids = _pad_pow2_ids(ids)
    packed = _is_packed(bundle)
    # direct-transfer bundles arrive ALREADY pow2-padded (gather width kept
    # across the wire), so the pad delta is vs the bundle's actual width,
    # not len(ids)
    missing = len(pids) - bundle.shape[1]
    if missing > 0:
        if isinstance(bundle, jax.Array):
            # device bundles pad on device — a numpy round-trip would stage
            # every page through host RAM
            pad = jnp.repeat(bundle[:, -1:], missing, axis=1)
            bundle = jnp.concatenate([bundle, pad], axis=1)
        else:
            pad = np.repeat(np.asarray(bundle[:, -1:]), missing, axis=1)
            bundle = np.concatenate([np.asarray(bundle), pad], axis=1)
    elif missing < 0:
        raise ValueError(
            f"bundle width {bundle.shape[1]} exceeds padded id count "
            f"{len(pids)} — ids and bundle disagree")
    if is_quant_cache(cache):
        if packed:
            if start_layer is not None:
                return _scatter_packed_layers(cache, jnp.asarray(pids),
                                              jnp.asarray(bundle),
                                              block_size=block_size,
                                              start_layer=int(start_layer))
            return _scatter_packed(cache, jnp.asarray(pids),
                                   jnp.asarray(bundle),
                                   block_size=block_size)
        if start_layer is not None:
            return _scatter_quant_layers(cache, jnp.asarray(pids),
                                         jnp.asarray(bundle, jnp.float32),
                                         block_size=block_size,
                                         start_layer=int(start_layer))
        return _scatter_quant(cache, jnp.asarray(pids),
                              jnp.asarray(bundle, jnp.float32),
                              block_size=block_size)
    if packed:  # quantized prefill → full-precision decode cache
        KV, hd = cache.shape[2], cache.shape[3]
        qb, sb = unpack_kv_blocks(jnp.asarray(bundle), block_size, KV, hd)
        bundle = dequantize_kv(qb, sb)
    if start_layer is not None:
        return _scatter_layers(cache, jnp.asarray(pids),
                               jnp.asarray(bundle).astype(cache.dtype),
                               block_size=block_size,
                               start_layer=int(start_layer))
    return _scatter(cache, jnp.asarray(pids),
                    jnp.asarray(bundle).astype(cache.dtype),
                    block_size=block_size)


def reshard_bundle(bundle: jax.Array, sharding) -> jax.Array:
    """Re-lay a KV bundle onto a different sharding (prefill-TP ≠ decode-TP).

    XLA lowers the device_put to the needed collective (all-to-all /
    all-gather over ICI) — the TPU counterpart of the reference's
    layout-transpose copy between prefill and decode workers
    (ref: docs/architecture/disagg_serving.md:103).
    """
    return jax.device_put(bundle, sharding)
