"""Runtime configuration from ``DYN_*`` environment variables.

Env-first configuration like the reference (ref: lib/runtime/src/config.rs):

- ``DYN_CONTROL_PLANE``  — ``host:port`` of the dynctl server; unset means
  single-process mode with an in-process control plane.
- ``DYN_LEASE_TTL``      — primary lease TTL seconds (default 10).
- ``DYN_NAMESPACE``      — default namespace (default ``dynamo``).
- ``DYN_LOG``            — log level (default info).
- ``DYN_LOGGING_JSONL``  — JSONL log lines when truthy.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field
from typing import Optional


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


@dataclass
class RuntimeConfig:
    control_plane_address: Optional[str] = field(
        default_factory=lambda: os.environ.get("DYN_CONTROL_PLANE")
    )
    lease_ttl: float = field(default_factory=lambda: _env_float("DYN_LEASE_TTL", 10.0))
    namespace: str = field(default_factory=lambda: os.environ.get("DYN_NAMESPACE", "dynamo"))

    @staticmethod
    def from_env() -> "RuntimeConfig":
        return RuntimeConfig()


def apply_platform_env() -> None:
    """Honor an explicit ``JAX_PLATFORMS`` even though the container's
    sitecustomize imports jax at interpreter startup and pins the axon TPU
    plugin (by then the env var is too late — jax.config must be used).
    Without this, CPU-only smoke runs of the worker mains hang trying to
    reach a TPU tunnel they were told not to use."""
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    try:
        import jax

        jax.config.update("jax_platforms", want)
    except Exception:  # jax absent (pure control-plane processes): fine
        pass


_LOGGING_CONFIGURED = False


def setup_logging():
    global _LOGGING_CONFIGURED
    if _LOGGING_CONFIGURED:
        return
    _LOGGING_CONFIGURED = True
    apply_platform_env()
    level = os.environ.get("DYN_LOG", "info").upper()
    if os.environ.get("DYN_LOGGING_JSONL"):
        fmt = ('{"ts":"%(asctime)s","level":"%(levelname)s",'
               '"target":"%(name)s","rid":"%(rid)s","msg":"%(message)s"}')
    else:
        fmt = "%(asctime)s %(levelname)-7s %(name)s [%(rid)s]: %(message)s"
    logging.basicConfig(level=getattr(logging, level, logging.INFO), format=fmt)

    # every record carries the current request id (trace correlation across
    # frontend and worker processes — ref: logging.rs:150-215)
    class _RidFilter(logging.Filter):
        def filter(self, record):
            from dynamo_tpu.runtime.context import CURRENT_REQUEST

            ctx = CURRENT_REQUEST.get()
            record.rid = ctx.id[:16] if ctx is not None else "-"
            return True

    for h in logging.getLogger().handlers:
        h.addFilter(_RidFilter())
