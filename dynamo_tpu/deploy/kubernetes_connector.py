"""Kubernetes planner connector: apply scaling decisions to a
DynamoGraphDeployment custom resource.

Rebuild of the reference's KubernetesConnector (ref: components/planner/src/
dynamo/planner/kubernetes_connector.py — patches the DynamoGraphDeployment
CRD's per-service replica counts; the operator's reconciler then realizes
them as pods). No kubernetes client library ships in this image, so the
patch rides ``kubectl`` (which handles kubeconfig/in-cluster auth); the
command runner is injectable for tests and alternative transports.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Callable, Optional

from dynamo_tpu.planner.planner_core import Decision

logger = logging.getLogger("dynamo.planner.k8s")

GRAPH_RESOURCE = "dynamographdeployment"


async def _kubectl(argv: list[str]) -> tuple[int, str]:
    """Default runner: kubectl subprocess (argv excludes the binary)."""
    proc = await asyncio.create_subprocess_exec(
        "kubectl", *argv,
        stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.STDOUT)
    out, _ = await proc.communicate()
    return proc.returncode, out.decode()


class _ScaleConnectorBase:
    """Shared decision→CR-merge-patch logic; subclasses supply the
    transport (``_patch``). Keeps the dedup short-circuit and patch shape
    in ONE place so kubectl and API transports can't drift."""

    prefill_service: str
    decode_service: str
    applied: Optional[Decision]

    def _build_patch(self, decision: Decision) -> dict:
        return {"spec": {"services": {
            self.prefill_service: {"replicas": int(decision.prefill_replicas)},
            self.decode_service: {"replicas": int(decision.decode_replicas)},
        }}}

    def _unchanged(self, decision: Decision) -> bool:
        return (self.applied is not None
                and decision.prefill_replicas == self.applied.prefill_replicas
                and decision.decode_replicas == self.applied.decode_replicas)

    async def apply(self, decision: Decision) -> None:
        if self._unchanged(decision):
            return
        if not await self._patch(self._build_patch(decision)):
            return  # keep self.applied unchanged so the next tick retries
        self.applied = decision
        logger.info("k8s scale applied: prefill=%d decode=%d",
                    decision.prefill_replicas, decision.decode_replicas)

    async def _patch(self, patch: dict) -> bool:
        raise NotImplementedError


class KubernetesConnector(_ScaleConnectorBase):
    """``apply(decision)`` → one JSON merge patch per changed service,
    applied via kubectl (kubeconfig/in-cluster auth handled by the CLI)."""

    def __init__(self, deployment: str, k8s_namespace: str = "default",
                 prefill_service: str = "prefill",
                 decode_service: str = "decode",
                 runner: Optional[Callable] = None):
        self.deployment = deployment
        self.k8s_namespace = k8s_namespace
        self.prefill_service = prefill_service
        self.decode_service = decode_service
        self.runner = runner or _kubectl
        self.applied: Optional[Decision] = None

    async def _patch(self, patch: dict) -> bool:
        rc, out = await self.runner([
            "-n", self.k8s_namespace, "patch", GRAPH_RESOURCE,
            self.deployment, "--type", "merge", "-p", json.dumps(patch)])
        if rc != 0:
            logger.error("kubectl patch failed (rc=%d): %s", rc, out.strip())
            return False
        return True

    async def read_replicas(self) -> Optional[dict]:
        """Observed spec replicas (for drift checks / tests)."""
        rc, out = await self.runner([
            "-n", self.k8s_namespace, "get", GRAPH_RESOURCE, self.deployment,
            "-o", "json"])
        if rc != 0:
            return None
        try:
            spec = json.loads(out).get("spec", {}).get("services", {})
            return {name: svc.get("replicas") for name, svc in spec.items()}
        except (ValueError, AttributeError):
            return None


class ApiKubernetesConnector(_ScaleConnectorBase):
    """Same contract as :class:`KubernetesConnector`, but PATCHes the CR
    through the Kubernetes REST API directly (deploy/kube_api.KubeClient) —
    no kubectl in the planner pod. The in-cluster controller
    (deploy/controller.py) observes the spec change via its watch and
    realizes it as pods; this is the reference's planner → CRD patch →
    reconciler flow end to end (ref: components/planner/src/dynamo/planner/
    kubernetes_connector.py)."""

    def __init__(self, client, deployment: str, k8s_namespace: str = "default",
                 prefill_service: str = "prefill",
                 decode_service: str = "decode"):
        from dynamo_tpu.deploy.controller import GROUP, PLURAL, VERSION

        self.deployment = deployment
        self.crs = client.resource(GROUP, VERSION, k8s_namespace, PLURAL)
        self.prefill_service = prefill_service
        self.decode_service = decode_service
        self.applied: Optional[Decision] = None

    async def _patch(self, patch: dict) -> bool:
        try:
            await self.crs.patch(self.deployment, patch)
            return True
        except Exception:
            logger.exception("CR patch failed; will retry next tick")
            return False

    async def read_replicas(self) -> Optional[dict]:
        try:
            obj = await self.crs.get(self.deployment)
        except Exception:
            return None
        spec = obj.get("spec", {}).get("services", {})
        return {name: svc.get("replicas") for name, svc in spec.items()}
