"""Tool-call parsers: extract structured calls from generated text.

ref: lib/parsers/src/tool_calling/ — per-model formats:

  hermes         <tool_call>{"name": …, "arguments": {…}}</tool_call>
  llama3_json    {"name": …, "parameters": {…}} (optionally after
                 <|python_tag|>; semicolon-separated for multiple calls)
  mistral        [TOOL_CALLS][{…}, …] (bracketed JSON array)
  phi4           functools[{…}, …]
  pythonic       [fn(a=1), other(b="x")] (llama-4 style python call list)
  nemotron_deci  <TOOLCALL>[{…}, …]</TOOLCALL> (ref: config.rs:92)
  deepseek_v3_1  <｜tool▁calls▁begin｜><｜tool▁call▁begin｜>name<｜tool▁sep｜>
                 {args}<｜tool▁call▁end｜><｜tool▁calls▁end｜>
                 (ref: config.rs:156, json/deepseek_parser.rs)
  harmony        gpt-oss channel markup (parsers/harmony.py;
                 ref: tool_calling/harmony/harmony_parser.rs)

Each parser returns (normal_text, [ToolCall]); detection is conservative —
text that doesn't parse stays ordinary content.
"""

from __future__ import annotations

import ast
import json
import re
import uuid
from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass
class ToolCall:
    name: str
    arguments: str  # JSON-encoded arguments object
    id: str = field(default_factory=lambda: f"call-{uuid.uuid4().hex[:24]}")

    def to_openai(self) -> dict:
        return {
            "id": self.id,
            "type": "function",
            "function": {"name": self.name, "arguments": self.arguments},
        }


def _mk(obj: dict) -> Optional[ToolCall]:
    name = obj.get("name")
    if not isinstance(name, str) or not name:
        return None
    args = obj.get("arguments", obj.get("parameters", {}))
    if isinstance(args, str):
        try:
            args = json.loads(args)
        except json.JSONDecodeError:
            return None
    return ToolCall(name=name, arguments=json.dumps(args))


# -- hermes -------------------------------------------------------------------

_HERMES_RE = re.compile(r"<tool_call>\s*(.*?)\s*</tool_call>", re.DOTALL)


def parse_hermes(text: str):
    calls = []
    for m in _HERMES_RE.finditer(text):
        try:
            tc = _mk(json.loads(m.group(1)))
        except json.JSONDecodeError:
            continue
        if tc:
            calls.append(tc)
    normal = _HERMES_RE.sub("", text).strip() if calls else text
    return normal, calls


# -- llama3 json --------------------------------------------------------------


def _split_top_level(s: str, sep: str) -> list[str]:
    """Split on sep only at brace/bracket depth 0 outside JSON strings."""
    parts, depth, in_str, esc, start = [], 0, False, False, 0
    for i, ch in enumerate(s):
        if in_str:
            if esc:
                esc = False
            elif ch == "\\":
                esc = True
            elif ch == '"':
                in_str = False
            continue
        if ch == '"':
            in_str = True
        elif ch in "{[":
            depth += 1
        elif ch in "}]":
            depth -= 1
        elif ch == sep and depth == 0:
            parts.append(s[start:i])
            start = i + 1
    parts.append(s[start:])
    return parts


def parse_llama3_json(text: str):
    stripped = text.strip()
    if stripped.startswith("<|python_tag|>"):
        stripped = stripped[len("<|python_tag|>"):]
    candidates = [c for c in (x.strip() for x in _split_top_level(stripped, ";"))
                  if c]  # tolerate trailing/doubled semicolons
    if not candidates:
        return text, []
    calls = []
    for c in candidates:
        if not (c.startswith("{") and c.endswith("}")):
            return text, []
        try:
            tc = _mk(json.loads(c))
        except json.JSONDecodeError:
            return text, []
        if tc is None:
            return text, []
        calls.append(tc)
    return "", calls


# -- mistral / phi4: marker + balanced JSON array ----------------------------


def _parse_marked_array(text: str, marker_re: re.Pattern):
    """Extract every marker-prefixed JSON array via raw_decode (balanced —
    a greedy regex would swallow trailing prose up to the last ']')."""
    calls: list[ToolCall] = []
    normal_parts: list[str] = []
    pos = 0
    while True:
        m = marker_re.search(text, pos)
        if not m:
            normal_parts.append(text[pos:])
            break
        try:
            arr, end = json.JSONDecoder().raw_decode(text, m.end())
        except json.JSONDecodeError:
            normal_parts.append(text[pos:])
            break
        block = [tc for obj in arr if isinstance(obj, dict) and (tc := _mk(obj))] \
            if isinstance(arr, list) else []
        if not block:
            normal_parts.append(text[pos:])
            break
        calls.extend(block)
        normal_parts.append(text[pos:m.start()])
        pos = end
    if not calls:
        return text, []
    return "".join(normal_parts).strip(), calls


_MISTRAL_RE = re.compile(r"\[TOOL_CALLS\]\s*(?=\[)")
_PHI4_RE = re.compile(r"functools\s*(?=\[)")


def parse_mistral(text: str):
    return _parse_marked_array(text, _MISTRAL_RE)


def parse_phi4(text: str):
    return _parse_marked_array(text, _PHI4_RE)


# -- nemotron_deci ------------------------------------------------------------

_NEMOTRON_RE = re.compile(r"<TOOLCALL>\s*(.*?)\s*</TOOLCALL>", re.DOTALL)


def parse_nemotron_deci(text: str):
    calls = []
    parsed_spans = []
    for m in _NEMOTRON_RE.finditer(text):
        try:
            arr = json.loads(m.group(1))
        except json.JSONDecodeError:
            continue  # unparseable block: stays in the normal text
        if isinstance(arr, list):
            block = [tc for obj in arr
                     if isinstance(obj, dict) and (tc := _mk(obj))]
            if block:
                calls.extend(block)
                parsed_spans.append(m.span())
    if not calls:
        return text, []
    out, pos = [], 0
    for a, b in parsed_spans:  # strip only the blocks that became calls
        out.append(text[pos:a])
        pos = b
    out.append(text[pos:])
    return "".join(out).strip(), calls


# -- deepseek_v3_1 ------------------------------------------------------------
# <｜tool▁calls▁begin｜><｜tool▁call▁begin｜>name<｜tool▁sep｜>{args}
# <｜tool▁call▁end｜>…<｜tool▁calls▁end｜> — the ▁/｜ glyphs are DeepSeek's
# fullwidth specials, kept verbatim (they arrive as detokenized text)

_DS_CALL_RE = re.compile(
    "<｜tool▁call▁begin｜>(.*?)<｜tool▁sep｜>(.*?)<｜tool▁call▁end｜>",
    re.DOTALL)
_DS_START = "<｜tool▁calls▁begin｜>"


def parse_deepseek_v3_1(text: str):
    trimmed = text.strip()
    i = trimmed.find(_DS_START)
    if i < 0:
        return text, []
    calls = []
    for name, args in _DS_CALL_RE.findall(trimmed):
        name = name.strip()
        if not name:
            continue
        try:
            parsed = json.loads(args.strip())
        except json.JSONDecodeError:
            continue  # ref: invalid JSON → skip the call
        calls.append(ToolCall(name=name, arguments=json.dumps(parsed)))
    if not calls:
        return text, []  # nothing parsed: caller's text verbatim
    # ref parity: normal text is everything BEFORE the calls block,
    # untouched (deepseek_parser.rs test pins the trailing space)
    return trimmed[:i], calls


# -- pythonic (llama-4) -------------------------------------------------------


def parse_pythonic(text: str):
    stripped = text.strip()
    if not (stripped.startswith("[") and stripped.endswith("]")):
        return text, []
    try:
        tree = ast.parse(stripped, mode="eval")
    except SyntaxError:
        return text, []
    if not isinstance(tree.body, ast.List):
        return text, []
    calls = []
    for el in tree.body.elts:
        if not (isinstance(el, ast.Call) and isinstance(el.func, ast.Name)):
            return text, []
        if el.args:  # positional args can't be named without the schema —
            return text, []  # reject rather than silently drop them
        args = {}
        for kw in el.keywords:
            if kw.arg is None:  # **kwargs form: reject like positionals
                return text, []
            try:
                args[kw.arg] = ast.literal_eval(kw.value)
            except (ValueError, SyntaxError):
                return text, []
        calls.append(ToolCall(name=el.func.id, arguments=json.dumps(args)))
    return "", calls


def _parse_harmony(text: str):
    from dynamo_tpu.parsers.harmony import parse_harmony

    return parse_harmony(text)


_PARSERS: dict[str, Callable] = {
    "hermes": parse_hermes,
    "llama3_json": parse_llama3_json,
    "mistral": parse_mistral,
    "phi4": parse_phi4,
    "pythonic": parse_pythonic,
    "nemotron_deci": parse_nemotron_deci,
    "deepseek_v3_1": parse_deepseek_v3_1,
    "harmony": _parse_harmony,
}


def get_tool_parser(name: Optional[str]) -> Optional[Callable]:
    if not name:
        return None
    return _PARSERS.get(name)


def parse_tool_calls(name: str, text: str):
    """(normal_text, [ToolCall]) for the named format; unknown name = no-op."""
    p = get_tool_parser(name)
    if p is None:
        return text, []
    return p(text)
