"""ctypes loader for the native C++ core (graceful pure-Python fallback).

``lib`` is None when libdynamo_native.so hasn't been built (see
native_build.py); callers must branch. Parity with the Python implementations
is enforced by tests/test_native.py.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

_SO = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "libdynamo_native.so")

lib: Optional[ctypes.CDLL] = None
if os.path.exists(_SO):
    try:
        lib = ctypes.CDLL(_SO)
        lib.dyn_xxh3_64.restype = ctypes.c_uint64
        lib.dyn_xxh3_64.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                    ctypes.c_uint64]
        lib.dyn_block_hashes.restype = ctypes.c_size_t
        lib.dyn_block_hashes.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t, ctypes.c_size_t,
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
    except OSError:
        lib = None


def xxh3_64(data: bytes, seed: int) -> Optional[int]:
    if lib is None:
        return None
    return lib.dyn_xxh3_64(data, len(data), seed & (2**64 - 1))


def block_hashes(tokens, block_size: int, salt: int):
    """(block_hashes, sequence_hashes) for complete blocks, or None."""
    if lib is None:
        return None
    import struct

    n_tokens = len(tokens)
    n = n_tokens // block_size
    if n == 0:
        return [], []
    # bulk-pack: per-element ctypes construction would dominate the call
    packed = struct.pack(f"<{n_tokens}I", *tokens)
    arr = ctypes.cast(ctypes.create_string_buffer(packed, len(packed)),
                      ctypes.POINTER(ctypes.c_uint32))
    out_b = (ctypes.c_uint64 * n)()
    out_s = (ctypes.c_uint64 * n)()
    lib.dyn_block_hashes(arr, n_tokens, block_size, salt & (2**64 - 1),
                         out_b, out_s)
    return list(out_b), list(out_s)
