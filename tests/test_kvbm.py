"""KVBM: tier LRU/cascade behavior and offload→clear→onboard determinism.

Mirrors the reference's determinism suite (ref: tests/kvbm/
test_determinism.py:577-919 — same prompts with/without offload + cache
reset must produce identical outputs).
"""

import asyncio

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineArgs, ModelConfig
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.kvbm import DiskTier, HostTier, KvbmManager
from dynamo_tpu.protocols import (
    PreprocessedRequest, SamplingOptions, StopConditions,
)

pytestmark = pytest.mark.anyio


def page(i, nbytes=256):
    return np.full((nbytes // 4,), i, np.float32)


def test_host_tier_lru_and_budget():
    t = HostTier(capacity_bytes=4 * 512)  # fits 4 (k,v) pairs of 256B each
    for i in range(4):
        assert t.put(i, page(i), page(i)) == []
    assert len(t) == 4
    t.get(0)  # refresh 0
    ev = t.put(9, page(9), page(9))
    assert [e[0] for e in ev] == [1]  # LRU (not 0) cascades out
    assert 0 in t and 9 in t and 1 not in t


def test_disk_tier_roundtrip(tmp_path):
    t = DiskTier(str(tmp_path), capacity_bytes=3 * 512)
    for i in range(5):
        t.put(i, page(i), page(i))
    assert len(t) == 3  # budget evicted the two oldest
    assert 0 not in t and 1 not in t
    k, v = t.get(4)
    np.testing.assert_array_equal(k, page(4))


def test_manager_cascade_and_promote(tmp_path):
    m = KvbmManager(host_bytes=2 * 512, disk_dir=str(tmp_path),
                    disk_bytes=16 * 512)
    for i in range(5):
        m.put(i, page(i), page(i))
    # 3 oldest cascaded to disk, 2 newest on host
    assert len(m.host) == 2 and len(m.disk) == 3
    assert m.match_prefix([0, 1, 2, 3, 4]) == 5
    k, _ = m.get(0)  # disk hit → promoted back to host
    np.testing.assert_array_equal(k, page(0))
    assert 0 in m.host


def make_engine(**kw) -> AsyncJaxEngine:
    cfg = ModelConfig.tiny()
    defaults = dict(block_size=4, num_blocks=64, max_num_seqs=8,
                    max_num_batched_tokens=64, max_model_len=256,
                    prefill_buckets=(8, 16, 32, 64),
                    decode_batch_buckets=(1, 2, 4, 8))
    defaults.update(kw)
    return AsyncJaxEngine(cfg, EngineArgs(**defaults))


def req(tokens, max_tokens=8) -> PreprocessedRequest:
    return PreprocessedRequest(
        model="tiny", token_ids=list(tokens),
        stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling_options=SamplingOptions(),
    )


async def collect(eng, r):
    toks = []
    async for out in eng.generate(r):
        toks.extend(out.token_ids)
    return toks


async def test_offload_clear_onboard_determinism():
    """Prompt served → device prefix cache cleared → same prompt again must
    onboard from the host tier and produce identical tokens."""
    prompt = list(range(1, 30))

    ref_eng = make_engine()
    want = await collect(ref_eng, req(prompt))
    await ref_eng.close()

    eng = make_engine(kvbm_host_bytes=64 << 20)
    got1 = await collect(eng, req(prompt))
    assert got1 == want
    # let async offloads drain
    for _ in range(50):
        if eng.kvbm.offloaded_blocks >= len(prompt) // 4:
            break
        await asyncio.sleep(0.02)
    assert eng.kvbm.offloaded_blocks > 0

    eng.pool.clear()  # admin clear: device prefix cache gone, tiers remain
    got2 = await collect(eng, req(prompt))
    assert got2 == want
    assert eng.kvbm.onboarded_blocks > 0  # prefix came back from G2
    assert eng.scheduler.prefix_hit_tokens > 0
    await eng.close()


async def test_onboard_from_disk_after_host_pressure(tmp_path):
    """Host tier too small to hold the prefix → blocks cascade to disk and
    still onboard correctly."""
    prompt = list(range(1, 30))
    ref_eng = make_engine()
    want = await collect(ref_eng, req(prompt))
    await ref_eng.close()

    cfg = ModelConfig.tiny()
    # one tiny block is L*bs*KV*hd*4B*2 — size host tier to ~2 blocks
    blk_bytes = 2 * cfg.num_layers * 4 * cfg.num_kv_heads * (
        cfg.hidden_size // cfg.num_heads) * 4
    eng = make_engine(kvbm_host_bytes=2 * blk_bytes,
                      kvbm_disk_dir=str(tmp_path),
                      kvbm_disk_bytes=64 << 20)
    got1 = await collect(eng, req(prompt))
    assert got1 == want
    for _ in range(50):
        if len(eng.kvbm.disk) > 0:
            break
        await asyncio.sleep(0.02)
    assert len(eng.kvbm.disk) > 0

    eng.pool.clear()
    # disk-resident prefix: the first admission does NOT block on np.load —
    # it schedules a G3→G2 promotion and recomputes. Outputs stay correct.
    got2 = await collect(eng, req(prompt))
    assert got2 == want
    # once promotion lands the prefix on host, the next cleared-cache
    # admission onboards it synchronously
    for _ in range(100):
        if len(eng.kvbm.host) >= 2:
            break
        await asyncio.sleep(0.02)
    eng.pool.clear()
    got3 = await collect(eng, req(prompt))
    assert got3 == want
    assert eng.kvbm.onboarded_blocks > 0
    await eng.close()
