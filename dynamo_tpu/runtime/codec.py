"""Length-prefixed msgpack framing for all runtime TCP planes.

Analog of the reference's ``TwoPartCodec`` (ref: lib/runtime/src/pipeline/
network/codec/two_part.rs:11): every frame is a 4-byte big-endian length
followed by a msgpack map. A frame's ``t`` field is its type tag; data planes
put the payload under ``d`` and an optional header under ``h`` — the two-part
(header, data) split the reference uses for control-vs-payload separation.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any

import msgpack

MAX_FRAME = 256 * 1024 * 1024  # 256 MiB hard cap (KV block transfers can be large)

_LEN = struct.Struct(">I")


def pack_frame(obj: Any) -> bytes:
    body = msgpack.packb(obj, use_bin_type=True)
    return _LEN.pack(len(body)) + body


async def read_frame(reader: asyncio.StreamReader) -> Any:
    """Read one frame; raises IncompleteReadError/ConnectionError on EOF."""
    hdr = await reader.readexactly(4)
    (n,) = _LEN.unpack(hdr)
    if n > MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    body = await reader.readexactly(n)
    return msgpack.unpackb(body, raw=False)


async def write_frame(writer: asyncio.StreamWriter, obj: Any) -> None:
    writer.write(pack_frame(obj))
    await writer.drain()
