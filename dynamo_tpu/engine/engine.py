"""AsyncJaxEngine: the native TPU token-generation engine.

The engine loop executes Scheduler plans as jitted steps:

    plan() → [prefill chunk jit call] + [decode batch jit call] → sample →
    commit bookkeeping → emit LLMEngineOutput per sequence → KV events

Static-shape discipline (XLA semantics — one trace per bucket): chunk
lengths, decode batch sizes, and block-table widths are padded to
EngineArgs buckets, so steady-state serving touches a handful of compiled
programs. Caches are donated through every call (no HBM copies).

This module is the TPU-native replacement for the reference's delegated
engine (ref: components/backends/vllm/src/dynamo/vllm/{main,handlers}.py);
its generate() contract matches the pipeline's EngineFn so it slots behind
Backend/Migration/Router operators unchanged.
"""

from __future__ import annotations

import asyncio
import collections
import functools
import itertools
import logging
import time
from typing import AsyncIterator, Callable, Optional

import numpy as np

from dynamo_tpu.engine.cache import (
    BlockPool, NULL_BLOCK, SwapStore, allocate_device_cache,
    hbm_sized_num_blocks,
)
from dynamo_tpu.engine.config import EngineArgs, ModelConfig
from dynamo_tpu.engine.scheduler import Scheduler, SeqState, StepPlan
from dynamo_tpu.protocols import FinishReason, LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.runtime.chaos import get_chaos as _get_chaos
from dynamo_tpu.runtime.context import StreamError
from dynamo_tpu.router.protocols import (
    ForwardPassMetrics, KvCacheEvent, KvStats, SpecDecodeStats, StoredBlock,
    WorkerStats,
)

logger = logging.getLogger("dynamo.engine")

#: standalone preempt-to-swap host budget when no G2 tier is configured
DEFAULT_SWAP_HOST_BYTES = 1 << 30


class _SwapEntry:
    """One swapped-out sequence's host-side KV bundle + budget reservation.

    Lifecycle: created (gather dispatched, budget reserved) → ready (host
    copy landed) → freed (swap-in consumed it, or teardown). ``dropped``
    marks a teardown that raced the in-flight copy — the copy task frees
    the reservation when it completes."""

    __slots__ = ("n", "nbytes", "k", "v", "ready", "failed", "freed",
                 "dropped")

    def __init__(self, n: int, nbytes: int):
        self.n = n              # device blocks captured
        self.nbytes = nbytes    # reserved against the SwapStore budget
        self.k = None           # host bundle [L, n, bs, KV, hd] or packed
        self.v = None
        self.ready = False
        self.failed = False
        self.freed = False
        self.dropped = False


def _has_penalties(s) -> bool:
    """True when the seq requests any sampling penalty (OpenAI presence/
    frequency over generated text, nvext/HF repetition over prompt+generated
    — ref: lib/llm/src/protocols/common.rs sampling options). Penalties need
    the per-step token history, so these rows are excluded from the fused
    burst and speculative paths."""
    so = s.req.sampling_options
    return bool(so.presence_penalty or so.frequency_penalty
                or (so.repetition_penalty not in (None, 1.0)))


def _guided_fsm(s):
    """The seq's device-compiled FSM cursor (structured/runtime.FsmCursor),
    or None for unconstrained rows AND host-oracle fallbacks. Device rows
    mask + advance inside the sampling dispatch, so they ride every fast
    path (ragged, pipelined, fused burst, spec verify)."""
    gs = s.guided_state
    return gs if gs is not None and getattr(gs, "device", False) else None


def _guided_host_only(s) -> bool:
    """True when the seq's constraint runs on the HOST oracle (table over
    budget, min_tokens EOS gating, multi-host, or --no-structured-device):
    it needs host-visible logits and a Python FSM advance per token, so it
    is excluded from the pipelined/burst/spec paths — the pre-structured
    behavior, now the exception instead of the rule."""
    gs = s.guided_state
    return gs is not None and not getattr(gs, "device", False)


class AsyncJaxEngine:
    """Continuously-batched paged-KV inference engine on JAX.

    Args:
      cfg/args: model + engine config.
      params: model params pytree (None → random init, tests/benches).
      mesh: optional jax Mesh with ("dp","tp") axes for sharded serving.
      event_cb: fn(KvCacheEvent) — KV events toward the router.
      metrics_cb: fn(ForwardPassMetrics) — per-step load metrics.
    """

    def __init__(self, cfg: ModelConfig, args: EngineArgs, params=None,
                 mesh=None, event_cb: Optional[Callable] = None,
                 metrics_cb: Optional[Callable] = None,
                 guided_vocab: Optional[list] = None):
        import jax
        from dynamo_tpu.engine import model as M

        self.cfg, self.args, self.mesh = cfg, args, mesh
        self.event_cb = event_cb
        self.metrics_cb = metrics_cb
        self._event_id = itertools.count()

        #: mesh spans processes? then arrays must be created as GLOBAL
        #: arrays (device_put cannot reach another host's devices) and every
        #: rank replays the same step order (parallel/multihost.py)
        self._multihost = False
        if mesh is not None:
            from dynamo_tpu.parallel.multihost import is_multihost
            self._multihost = is_multihost(mesh)
        #: leader hook: called with (kind, host_arrays) right before each
        #: jitted dispatch so follower ranks stay in SPMD lockstep
        self.broadcast_cb: Optional[Callable] = None

        if params is None:
            params = M.init_params(cfg, jax.random.key(args.seed))
        if args.quantization is not None:
            from dynamo_tpu.engine.quant import quantize_params
            # host-side quantization (numpy): the bf16 original never has
            # to coexist with the quantized copy in HBM. Idempotent —
            # leaves already quantized at load (MXFP4/GGUF) pass through
            params = quantize_params(
                jax.tree.map(np.asarray, params), args.quantization)
            if mesh is None:
                # the host-side walk left every leaf as numpy; put the tree
                # back on device or each jitted step re-uploads it
                params = jax.device_put(params)
        if mesh is not None:
            from dynamo_tpu.engine.quant import quant_shardings
            sh = M.param_shardings(cfg, mesh)
            # no-op on unquantized trees; mirrors weight shardings onto
            # QTensor subtrees (q like the weight, scales' group dim
            # replicated) for load-time-quantized checkpoints too
            sh = quant_shardings(sh, params)
            if self._multihost:
                from dynamo_tpu.parallel.multihost import global_put
                params = jax.tree.map(global_put, params, sh)
            else:
                params = jax.device_put(params, sh)
        self.params = params

        self._pp = mesh.shape.get("pp", 1) if mesh is not None else 1
        if self._pp > 1:
            from dynamo_tpu.parallel.pipeline import pp_compatible
            reason = pp_compatible(cfg, self._pp)
            if reason is not None:
                # a pp fleet silently serving un-pipelined would run at a
                # fraction of its planned capacity — fail loudly
                raise ValueError(f"pp_size={self._pp}: {reason}")

        self._kv_quant = args.kv_cache_dtype == "int8"
        # capability gaps fail loudly at construction: a fleet silently
        # running a degraded configuration would serve at a fraction of its
        # planned capacity with nothing but a log line to show for it
        if self._pp > 1:
            if self._kv_quant:
                raise ValueError(
                    "kv_cache_dtype='int8' is not supported under pipeline "
                    "parallelism (pp_size=%d); use the model dtype or pp=1"
                    % self._pp)
            if args.multi_step_decode > 1:
                raise ValueError(
                    "multi_step_decode=%d is not supported under pipeline "
                    "parallelism (pp_size=%d); set multi_step_decode=1"
                    % (args.multi_step_decode, self._pp))
            if args.speculative_tokens > 0:
                raise ValueError(
                    "speculative_tokens=%d is not supported under pipeline "
                    "parallelism (pp_size=%d); set speculative_tokens=0"
                    % (args.speculative_tokens, self._pp))
        from dynamo_tpu.engine.cache import tree_nbytes
        # tree_nbytes is GLOBAL bytes; the fallback estimator reasons about
        # ONE chip's HBM, and TP shards the big weight matrices across
        # chips (replicated norm/scale leaves are noise at this precision)
        nb = args.num_blocks or hbm_sized_num_blocks(
            cfg, args.block_size, args.kv_cache_memory_fraction, args.tp_size,
            kv_cache_dtype="int8" if self._kv_quant else None,
            params_bytes=tree_nbytes(self.params) // max(1, args.tp_size))
        self.num_blocks = nb
        self.k_cache, self.v_cache = allocate_device_cache(
            cfg, nb, args.block_size, mesh, global_arrays=self._multihost,
            dtype="int8" if self._kv_quant else None)

        #: silent-fallback visibility (docs/performance.md "Quantized
        #: serving"): static reason the ragged step degrades to the XLA
        #: attention path (None = Pallas ragged kernel on the path, or
        #: never requested). A degraded launch is a silent TTFT/HBM
        #: regression — log it ONCE here, count every degraded step into
        #: dynamo_ragged_fallback_total{reason}, and tag the flight record.
        self.ragged_fallback_reason = M.ragged_fallback_reason(
            cfg, mesh, args.use_pallas_attention, self._kv_quant,
            nb * args.block_size)
        self.ragged_fallback_total: dict = {}
        if self.ragged_fallback_reason is not None:
            logger.warning(
                "ragged Pallas kernel unavailable (reason=%s): steps take "
                "the XLA attention path — counted in "
                "dynamo_ragged_fallback_total", self.ragged_fallback_reason)

        #: per-tier residency ledger (observability/kvaudit.py): the
        #: worker-side ground truth the KV audit plane compares the
        #: router's radix view against — rolling xor/count digests folded
        #: inline at register/evict/tier-change, served via the
        #: ``kv_digest`` wire op (engine/main.py)
        from dynamo_tpu.observability.kvaudit import WorkerKvLedger
        self.kv_ledger = WorkerKvLedger()
        self.kvbm = None
        if args.kvbm_host_bytes > 0 and args.enable_prefix_caching:
            from dynamo_tpu.kvbm import KvbmManager
            self.kvbm = KvbmManager(args.kvbm_host_bytes,
                                    disk_dir=args.kvbm_disk_dir,
                                    disk_bytes=args.kvbm_disk_bytes,
                                    # router-facing removed events fire
                                    # only when the LAST tier copy dies
                                    # (KvbmWorkerService chains onto this)
                                    on_change=self._on_kvbm_change,
                                    ledger=self.kv_ledger)
        #: set by engine/main.py when a distributed KVBM fleet is configured
        #: (RemoteKvbm — leader lookup + peer fetch)
        self.kvbm_remote = None
        self._offload_tasks: set = set()
        #: G4 prefix flow-up (docs/performance.md): prefix-cache hit
        #: counts per sequence hash; a block crossing the threshold is
        #: pushed to the fleet-global object store so cold workers can
        #: warm from it. 0 disables the flow-up (G4 then fills only via
        #: the eviction cascade, as before).
        import os as _os

        raw_hits = _os.environ.get("DYN_G4_PUBLISH_HITS")
        if raw_hits in (None, ""):
            self._g4_publish_hits = 2
        elif raw_hits in ("0", "off", "false"):
            self._g4_publish_hits = 0
        else:
            try:
                self._g4_publish_hits = int(raw_hits)
            except ValueError:
                # same startup-clarity contract as the DYN_ONBOARD_* /
                # DYN_RESTORE_* knobs (transfer._env_caster)
                raise ValueError(
                    f"bad DYN_G4_PUBLISH_HITS={raw_hits!r}") from None
        self._prefix_hits: dict = {}
        self._g4_publishing: set = set()

        self.pool = BlockPool(nb, args.enable_prefix_caching,
                              on_removed=self._on_removed,
                              ledger=self.kv_ledger)
        #: preempt-to-swap: host staging for preempted sequences' KV
        #: (scheduler-driven swap-out/swap-in replacing recompute). Budget
        #: shares the G2 tier's allowance when one is configured. Disabled
        #: under multi-host step replication: the gather/scatter dispatches
        #: are leader-local and would desync the follower replay.
        self._swap: Optional[SwapStore] = None
        if args.preempt_swap and not self._multihost:
            budget = args.swap_host_bytes
            shared = room = None
            if budget is None:
                if self.kvbm is not None:
                    budget = args.kvbm_host_bytes
                    shared = lambda: self.kvbm.host.used  # noqa: E731
                    # a full G2 LRU yields DRAM to swap reservations —
                    # without this, steady-state offload traffic would
                    # permanently starve swap of the shared allowance
                    room = self.kvbm.make_host_room
                else:
                    budget = DEFAULT_SWAP_HOST_BYTES
            self._swap = SwapStore(budget, external_used=shared,
                                   make_room=room)
            if shared is not None:
                # both directions of the shared allowance: G2 puts evict
                # down to (budget − swap reservations), so combined host
                # residency stays inside the ONE configured budget
                self.kvbm.host.external_used = lambda: self._swap.used
        self.swap_out_blocks = 0
        self.swap_in_blocks = 0
        #: ragged step (docs/performance.md): mixed prefill+decode in ONE
        #: packed launch, the ONLY step path — compiled signatures collapse
        #: to the token buckets, the scheduler plans a token budget per
        #: step, and padded dispatch between buckets is gone. Every mode
        #: (spec verify, MLA/TPLA, pp, multi-host, multi-step) rides the
        #: same packed layout.
        self.scheduler = Scheduler(
            args, self.pool, on_stored=self._on_stored,
            onboard_cb=self._onboard if self.kvbm is not None else None,
            swapper=self if self._swap is not None else None,
            token_budget=True,
            hot_cb=self._note_hot_prefix if self.kvbm is not None else None)
        self.pp_fn = None
        self.ragged_fn = None
        self.ragged_dec_fn = None
        self._ragged_mm_fn = None  # compiled lazily on first mm request
        self.multi_fn = None
        self.verify_fn = None
        self.draft_fn = None
        if self._pp > 1:
            from dynamo_tpu.parallel.pipeline import make_pp_step_fn
            # pp takes packed ragged microbatches: each microbatch is one
            # ragged bin with the same (T, R, C, W) shape, so the compiled
            # signature is (T, M) — no bucketed lattice per stage
            self.pp_fn = make_pp_step_fn(
                cfg, args.block_size, mesh,
                replicate_logits=self._multihost)
        else:
            self.ragged_fn = M.make_ragged_step_fn(
                cfg, args.block_size, mesh,
                use_pallas=args.use_pallas_attention,
                replicate_logits=self._multihost,
                kv_quant=self._kv_quant)
            # decode-only variant (no chunk grid): what decode-only plans
            # and the pipelined decode loop dispatch
            self.ragged_dec_fn = M.make_ragged_step_fn(
                cfg, args.block_size, mesh,
                use_pallas=args.use_pallas_attention,
                replicate_logits=self._multihost,
                kv_quant=self._kv_quant, chunks=False)
            if args.multi_step_decode > 1:
                self.multi_fn = M.make_multi_decode_fn(
                    cfg, args.block_size, args.multi_step_decode, mesh,
                    use_pallas=args.use_pallas_attention,
                    replicate_outputs=self._multihost,
                    kv_quant=self._kv_quant)
            if args.speculative_tokens > 0:
                # verify is a ragged row with q_len = draft+1
                self.verify_fn = M.make_ragged_verify_fn(
                    cfg, args.block_size, mesh,
                    replicate_outputs=self._multihost,
                    kv_quant=self._kv_quant)
                if args.speculative_method == "draft_layers":
                    self.draft_fn = M.make_draft_fn(
                        cfg, args.block_size, args.speculative_draft_layers,
                        args.speculative_tokens, mesh,
                        use_pallas=args.use_pallas_attention,
                        replicate_outputs=self._multihost,
                        kv_quant=self._kv_quant)
        self.spec_stats = SpecDecodeStats()
        #: speculative-decode auto-disable governor: rolling emitted-tokens
        #: window; when the measured gain stays < 1 the engine falls back to
        #: plain decode and re-probes after spec_reprobe_steps
        self._spec_window: "collections.deque" = collections.deque(
            maxlen=max(1, args.spec_gain_window))
        self._spec_resume_step = 0
        self.spec_disabled_total = 0
        self.spec_measured_gain: Optional[float] = None
        #: measured dispatch walls (EWMA, ms): one spec round (draft +
        #: verify + host round trip) vs one plain decode step — the
        #: governor's ragged cost re-baseline (_spec_dispatch_cost)
        self._spec_round_ms: Optional[float] = None
        self._decode_step_ms: Optional[float] = None
        from dynamo_tpu.engine import sampling as S
        self._sampling = S

        #: id → token text, for guided decoding's token-level DFA walks
        #: (engine/main.py decodes it from the served tokenizer); None =
        #: guided requests are refused
        self.guided_vocab = guided_vocab
        #: structured decoding (docs/structured.md): the device FSM arena
        #: constraints compile into. None = every constraint runs on the
        #: host oracle (no vocab, --no-structured-device, DYN_STRUCTURED=0,
        #: multi-host step replication — the arena uploads are leader-local
        #: and would desync follower replay, or a byte budget too small for
        #: this vocab width).
        self.structured = None
        if (args.structured_device and guided_vocab is not None
                and not self._multihost):
            from dynamo_tpu.structured import (
                StructuredRuntime, arena_states, env_enabled,
                table_budget_bytes,
            )
            if env_enabled():
                cap = arena_states(cfg.vocab_size,
                                   table_budget_bytes(args.structured_table_mb))
                if cap:
                    self.structured = StructuredRuntime(cfg.vocab_size, cap)
                else:
                    logger.info(
                        "structured device tables disabled: budget buys "
                        "too few states at vocab %d (DYN_STRUCTURED_TABLE_MB)",
                        cfg.vocab_size)
        #: lazily-compiled structured variants of the fused paths (first
        #: constrained request on each path pays one trace)
        self._multi_fsm_fn = None
        self._verify_masked_fn = None
        self._seq_counter = itertools.count()
        self._wake = asyncio.Event()
        # memory-starved plan(): park on _wake instead of hot-polling; a
        # BlockPool release (seq finish, offload unpin, abort) is the event
        # that can make the next plan() non-empty
        self.pool.on_freed = self._wake.set
        self._task: Optional[asyncio.Task] = None
        self._loop_ref = None  # captured by _ensure_loop (thread bridges)
        self._closed = False
        self.steps = 0
        #: decode steps executed by the depth-2 pipelined loop (telemetry:
        #: nonzero means the e2e path is actually overlapping copy/commit
        #: with device compute)
        self.pipelined_steps = 0
        #: jitted full-model forward passes (each reads every weight once
        #: from HBM) — the denominator for roofline/MFU accounting in bench.py
        self.param_reads = 0
        #: padded-dispatch waste: tokens (and decode batch rows) dispatched
        #: beyond the plan's REAL work because static shapes bucket up —
        #: the cost the ragged step eliminates. Exported as
        #: dynamo_step_padded_tokens_total (engine/main.py); per-step
        #: values ride the step trace.
        self.padded_tokens_total = 0
        #: distinct jitted step signatures dispatched so far (kind + static
        #: shape tuple) — len() is dynamo_step_compiled_signatures, the
        #: bucket-lattice-vs-ragged contrast on /metrics
        self.compiled_signatures: set = set()
        #: AOT warmup bookkeeping: ``warmup_skipped`` marks a worker whose
        #: requested warmup could not run (multi-host step replication) —
        #: surfaced via WorkerStats.warmed_up so the autoscale readiness
        #: gate does not count a cold worker as warm (docs/autoscaling.md)
        self.warmup_requested = args.warmup_buckets
        self.warmup_skipped = False
        #: per-step phase timing ring (kind, n_seqs, n_tokens, wall_ms) —
        #: the profile that located the r4 serving-vs-kernel gap; cheap
        #: enough to keep always-on, dumped by step_trace_summary()
        self.step_trace: "collections.deque" = collections.deque(maxlen=2048)
        #: step flight recorder (observability/flight.py): one structured,
        #: anomaly-tagged record per executed step — the fleet-queryable
        #: "why was this step slow" layer the step_trace ring cannot answer
        from dynamo_tpu.observability.flight import (
            FlightRecorder, register_recorder,
        )
        self.flight = FlightRecorder(service="engine")
        self._flight_name = register_recorder("engine", self.flight)
        #: anomaly-triggered bounded jax.profiler capture (None unless
        #: DYN_PROFILE_ON_ANOMALY names a directory): a slow-step /
        #: compile-steady flight tag arms one device-trace capture whose
        #: artifact path lands on the triggering StepRecord
        #: (observability/profiler.py AnomalyProfiler)
        from dynamo_tpu.observability.profiler import AnomalyProfiler
        self.anomaly_profiler = AnomalyProfiler.from_env()
        #: last-seen cumulative totals, differenced into per-step flight
        #: record deltas (preemptions, swap block movement)
        self._flight_last: dict = {}
        #: post-warmup jit traces observed at the serving dispatch sites:
        #: kind → count / total seconds (→ dynamo_compile_total{kind} /
        #: dynamo_compile_seconds_total{kind} in engine/main.py; the
        #: unlabeled dynamo_compile_seconds histogram rides the tracer's
        #: SLO registry). A compile after FLIGHT steady_after steps logs a
        #: WARNING with the offending signature — a mid-traffic compile
        #: used to be silent except as a latency cliff.
        self.compile_events: dict[str, int] = {}
        self.compile_seconds: dict[str, float] = {}
        self._last_compile: Optional[tuple] = None  # (kind, sig, seconds)
        self._last_dispatch_ms = 0.0  # latest jitted-call dispatch wall
        #: bytes per KV block (both caches, quant scales included) —
        #: computed lazily once for the G1 tier-occupancy gauge
        self._kv_block_nbytes: Optional[int] = None
        #: tier snapshot throttle for the flight record hot path (the
        #: pipelined decode loop records per step): occupancy moves at
        #: block-allocation cadence, so a 50 ms-old snapshot is current
        self._flight_tiers: dict = {}
        self._flight_tiers_t = 0.0
        self._last_empty_rec = 0.0  # empty-bubble record rate limit
        #: multi-process DP fleet rank (None = single-rank); reported in
        #: worker stats (ref: kv_router/protocols.rs:57 data_parallel_rank)
        self.dp_rank: Optional[int] = None
        #: direct device-to-device KV transfer for disagg (NIXL analog);
        #: None = host-staged bundles only
        self.direct_transfer = None
        if args.kv_transfer_direct:
            from dynamo_tpu.disagg.transfer import DirectTransferManager
            self.direct_transfer = DirectTransferManager()
        #: chaos ``worker.kill`` (runtime/chaos.py): True once this engine
        #: hard-died mid-step. The loop stops WITHOUT failing in-flight
        #: sinks (a SIGKILLed process completes nothing) — consumers hang
        #: until lease expiry breaks their streams, which is exactly the
        #: path stateful migration must survive (docs/robustness.md).
        self.killed = False
        #: fired (sync, best-effort) when worker.kill trips: mains use it
        #: to os._exit(137); in-process fleets to ServeHandle.kill() and
        #: to stop the worker's lease keepalive
        self.on_kill: list = []

    def direct_capability(self) -> Optional[str]:
        """Annotation a decode worker sends so prefill can offer direct
        device-to-device KV pulls (disagg/transfer.py)."""
        if self.direct_transfer is None:
            return None
        return self.direct_transfer.capability()

    # ------------------------------------------------------------------ api

    async def _new_seq(self, req: PreprocessedRequest, ctx, sink,
                       **kw) -> SeqState:
        """Build a SeqState — the ONE place request-scoped engine state
        (like the guided-decoding cursor) attaches, so every entry path
        (generate, disagg prefill_extract, generate_prefilled/injected)
        honors it."""
        if req.mm_embeds and self._pp > 1:
            # admission-time refusal: raising mid-step (inside _run_ragged)
            # would fail every in-flight sequence in the batch, not just
            # this request
            raise ValueError("multimodal requests are not supported under "
                             "pipeline parallelism yet")
        seq = SeqState(request_id=f"seq-{next(self._seq_counter)}",
                       req=req, ctx=ctx or _NullCtx(), sink=sink, **kw)
        if req.sampling_options.guided:
            from dynamo_tpu.structured import build_guided_state
            if self.guided_vocab is None:
                raise ValueError(
                    "guided decoding requested but this worker has no "
                    "tokenizer vocabulary (engine started without "
                    "guided_vocab)")
            # off the event loop: a cold constraint compiles the char NFA,
            # walks the vocab per visited DFA state, AND packs the device
            # tables; everything is cached so session turn 2+ is a dict hit.
            # min_tokens rows stay on the host oracle — its EOS suppression
            # depends on per-step generated counts the static tables can't
            # express (docs/structured.md fallback rules).
            seq.guided_state = await asyncio.to_thread(
                build_guided_state, req.sampling_options.guided,
                self.guided_vocab, req.eos_token_ids or [],
                self.structured,
                not (req.stop_conditions.min_tokens or 0) > 0)
        return seq

    async def generate(self, req: PreprocessedRequest, ctx=None
                       ) -> AsyncIterator[LLMEngineOutput]:
        """EngineFn-compatible async stream of per-token outputs."""
        from dynamo_tpu.observability import get_tracer

        from dynamo_tpu.observability.flight import flight_instance

        self._ensure_loop()
        sink: asyncio.Queue = asyncio.Queue()
        seq = await self._new_seq(req, ctx, sink)
        self.scheduler.add(seq)
        self._wake.set()
        # phase timing: queue+prefill until the first token (engine-side
        # TTFT), then the decode loop until finish — recorded as spans on
        # the request's trace (no-op for trace-less contexts). The spans
        # carry this worker's flight identity + the step-seq interval so
        # the attribution join (observability/attribution.py) can select
        # exactly the StepRecords that overlapped this request's life.
        tracer = get_tracer()
        t0 = time.time()
        seq0 = self.flight.seq_now
        seq_first = None
        t_first = None
        n_tokens = 0
        try:
            while True:
                out: Optional[LLMEngineOutput] = await sink.get()
                if out is None:
                    return
                if isinstance(out, Exception):
                    raise out  # chaos/step failure: surfaces as StreamError
                if t_first is None and out.token_ids:
                    t_first = time.time()
                    seq_first = self.flight.seq_now
                    tracer.record("engine.ttft", ctx, start=t0, end=t_first,
                                  service="engine",
                                  prompt_tokens=len(req.token_ids),
                                  flight_instance=flight_instance(),
                                  flight_name=self._flight_name,
                                  seq0=seq0, seq1=seq_first)
                    # first-frame flight identity: Migration reads it so a
                    # later re-send's restore hint can name THIS worker as
                    # the predecessor leg (prev_worker/prev_seq)
                    out.flight = {"worker": flight_instance(),
                                  "recorder": self._flight_name,
                                  "seq": seq_first}
                n_tokens += len(out.token_ids)
                yield out
                if out.finish_reason is not None:
                    return
        finally:
            if t_first is not None:
                tracer.record("engine.decode", ctx, start=t_first,
                              end=time.time(), service="engine",
                              tokens=n_tokens,
                              flight_instance=flight_instance(),
                              flight_name=self._flight_name,
                              seq0=seq_first, seq1=self.flight.seq_now)

    # ---------------------------------------------------------- embeddings

    async def embed(self, token_id_lists: list[list[int]]) -> list[list[float]]:
        """Mean-pooled L2-normalized embeddings for a batch of token lists
        (ref surface: /v1/embeddings, openai.rs:714). Runs the SERVING
        forward over a scratch paged cache, so every family the engine
        generates with (MLA, gpt-oss, MoE, …) embeds too. Shapes bucket to
        powers of two so steady traffic reuses a handful of programs."""
        if not token_id_lists:
            return []
        # bound inputs by the serving context the same way generate does
        # (an unbounded S — or an unbounded batch of near-limit inputs —
        # would OOM the worker)
        limit = self.args.max_model_len
        too_long = max(len(t) for t in token_id_lists)
        if too_long > limit:
            raise ValueError(
                f"embedding input of {too_long} tokens exceeds "
                f"max_model_len {limit}")
        total = len(token_id_lists) * too_long  # padded batch footprint
        budget = max(4096, 8 * limit)
        if total > budget:
            raise ValueError(
                f"embedding batch of {len(token_id_lists)}×{too_long} tokens "
                f"exceeds the per-request budget {budget}; split the batch")
        bs = self.args.block_size
        B = 1 << (len(token_id_lists) - 1).bit_length()
        S = max(bs, 1 << (too_long - 1).bit_length())
        if self._multihost:
            # the batch axis shards over "dp" under a global mesh; a bucket
            # narrower than the dp extent cannot be laid out
            B = max(B, self.mesh.shape.get("dp", 1))
        tokens = np.zeros((B, S), np.int32)
        lengths = np.zeros((B,), np.int32)
        for i, ids in enumerate(token_id_lists):
            tokens[i, :len(ids)] = ids
            lengths[i] = len(ids)

        if self._multihost:
            # broadcast + dispatch ON the event-loop thread: follower replay
            # order must match the leader's device dispatch order, and every
            # other step kind dispatches from this thread (a to_thread embed
            # could interleave differently on leader vs followers and wedge
            # the fleet in mismatched collectives)
            self._broadcast("embed", tokens=tokens, lengths=lengths)
            out = self._embed_forward(tokens, lengths)
            host = await asyncio.to_thread(np.asarray, out)
        else:
            def run():  # compile/dispatch + host copy off the event loop
                return np.asarray(self._embed_forward(tokens, lengths))

            host = await asyncio.to_thread(run)
        return [host[i].tolist() for i in range(len(token_id_lists))]

    def _embed_forward(self, tokens: np.ndarray, lengths: np.ndarray):
        """Setup (jitted fn + scratch caches) and dispatch of one embed
        forward — shared verbatim by the leader path and the follower's
        step replay so both ranks compile the identical program."""
        from dynamo_tpu.engine import model as M
        from dynamo_tpu.engine.cache import allocate_device_cache

        if getattr(self, "_embed_fn", None) is None:
            # one jitted callable (jax.jit re-specializes per (B,S) bucket)
            # + per-bucket scratch caches, reused across calls
            self._embed_fn = M.make_embed_fn(
                self.cfg, self.args.block_size, self.mesh,
                use_pallas=self.args.use_pallas_attention,
                replicate_outputs=self._multihost)
            self._embed_caches: dict = {}
        bs = self.args.block_size
        B, S = tokens.shape
        caches = self._embed_caches.get((B, S))
        if caches is None:
            # keep ONE scratch cache: mixed-shape embed traffic must not
            # accumulate per-bucket HBM the serving pool never budgeted
            # for (re-allocating on a shape change beats an OOM)
            self._embed_caches.clear()
            caches = allocate_device_cache(
                self.cfg, B * (S // bs) + 1, bs, self.mesh,
                global_arrays=self._multihost)
            self._embed_caches[(B, S)] = caches
        return self._embed_fn(self.params, self._put_batch("tokens", tokens),
                              self._put_batch("lengths", lengths), *caches)

    async def embed_handler(self, request: dict, ctx=None):
        """Endpoint handler: {"token_ids": [[...]]} → one embeddings frame."""
        try:
            vecs = await self.embed(request.get("token_ids") or [])
        except ValueError as e:  # input too long: client error, not a crash
            yield {"error": str(e)}
            return
        yield {"embeddings": vecs}

    # ------------------------------------------------------- disagg support

    async def prefill_extract(self, req: PreprocessedRequest, ctx=None):
        """Run prefill only and hand back (first token, logprob, KvBundle).

        The disagg prefill-worker path (ref: vllm/handlers.py:211-245 —
        max_tokens=1 generation returning kv_transfer_params); here the
        "transfer params" ARE the gathered pages.
        """
        import dataclasses

        from dynamo_tpu.disagg.protocols import KvBundle, PrefillResponse
        from dynamo_tpu.ops.block_copy import gather_blocks

        self._ensure_loop()
        t0 = time.time()
        sc = dataclasses.replace(req.stop_conditions, max_tokens=1,
                                 min_tokens=1, ignore_eos=True)
        preq = dataclasses.replace(req, stop_conditions=sc)
        sink: asyncio.Queue = asyncio.Queue()
        seq = await self._new_seq(preq, ctx, sink, hold_blocks=True)
        self.scheduler.add(seq)
        self._wake.set()
        token, logp = None, None
        try:
            while True:
                out = await sink.get()
                if out is None or isinstance(out, Exception):
                    break  # step failure: graceful token_id=-1 fallback below
                if out.token_ids:
                    token, logp = out.token_ids[0], (out.log_probs or [None])[0]
                if out.finish_reason is not None:
                    break
            if token is None:
                return PrefillResponse(token_id=-1, logprob=None, bundle=None)
            bs = self.args.block_size
            n = (seq.prompt_len + bs - 1) // bs
            ids = seq.block_table[:n]
            kb = gather_blocks(self.k_cache, ids, block_size=bs)
            vb = gather_blocks(self.v_cache, ids, block_size=bs)
            # gather pads the id list to a power of two (compile-cache
            # friendliness); slice back to the real block count host-side
            bundle = KvBundle(k=np.asarray(kb)[:, :n], v=np.asarray(vb)[:, :n],
                              num_tokens=seq.prompt_len, block_size=bs)
            return PrefillResponse(token_id=token, logprob=logp, bundle=bundle)
        finally:
            # covers cancellation at any point: pending/running seqs are
            # reaped with their blocks; finished ones release the held blocks
            self.scheduler.abort(seq)
            self._wake.set()
            from dynamo_tpu.observability import get_tracer

            get_tracer().record("prefill.extract", ctx, start=t0,
                                end=time.time(), service="engine",
                                prompt_tokens=len(req.token_ids),
                                streamed=False)

    async def prefill_extract_stream(self, req: PreprocessedRequest, ctx=None):
        """Pipelined prefill: yields KvChunkFrame wires for blocks whose KV is
        final WHILE later chunks are still computing, then the final
        PrefillResponse with the unshipped tail.

        The TPU analog of NIXL's compute-overlapped block transfer (ref:
        docs/architecture/disagg_serving.md:92-103): by the time the last
        chunk finishes, most pages are already on the decode worker.
        """
        import dataclasses

        from dynamo_tpu.disagg.protocols import (
            KvBundle, KvChunkFrame, KvLayerFrame, PrefillResponse,
        )
        from dynamo_tpu.disagg.transfer import KvDirectFrame
        from dynamo_tpu.ops.block_copy import gather_blocks

        self._ensure_loop()
        # direct device-to-device mode when the decode worker's capability
        # annotation says the pull can succeed (disagg/transfer.py); pages
        # then never touch the host on this side — only descriptors ship
        mode = (self.direct_transfer.choose_mode(req.annotations)
                if self.direct_transfer is not None else None)
        # layer-interleaved tail (docs/disagg.md): when negotiated, the
        # FINAL chunk's blocks are not shipped as one full-depth frame at
        # its commit — they ride the tail path below, split on the layer
        # axis so early layers' wire/scatter overlaps later layers' staging
        layer_groups = self._kv_layer_groups(req.annotations)
        bs = self.args.block_size
        sc = dataclasses.replace(req.stop_conditions, max_tokens=1,
                                 min_tokens=1, ignore_eos=True)
        preq = dataclasses.replace(req, stop_conditions=sc)
        sink: asyncio.Queue = asyncio.Queue()
        events: asyncio.Queue = asyncio.Queue()
        state = {"shipped": 0}  # full blocks whose gather is dispatched

        # The device gather MUST be dispatched inside the progress callback
        # (engine-loop context, right after the chunk commits): the block
        # table is valid at that instant, and the dispatched gather captures
        # the current immutable cache array — a later preemption only
        # releases host-side bookkeeping, the captured data stays correct.
        # Shipping is monotonic; a preemption recompute re-fires progress
        # with smaller ends, which are skipped (identical content anyway).
        def on_progress(end: int) -> None:
            if layer_groups is not None and end >= seq.prompt_len:
                return  # final commit: the whole last chunk is the tail
            full = end // bs
            if full <= state["shipped"]:
                return
            # backpressure: if the consumer (response plane) is behind, skip
            # this ship — unshipped blocks ride the next progress event or
            # the tail bundle, instead of piling duplicate KV copies in HBM
            if events.qsize() >= 4:
                return
            ids = seq.block_table[state["shipped"]:full]
            kb = gather_blocks(self.k_cache, ids, block_size=bs)
            vb = gather_blocks(self.v_cache, ids, block_size=bs)
            events.put_nowait(("chunk", (state["shipped"], len(ids), kb, vb)))
            state["shipped"] = full

        seq = await self._new_seq(preq, ctx, sink, hold_blocks=True,
                            progress_cb=on_progress)

        async def drain_sink():
            while True:
                out = await sink.get()
                if isinstance(out, Exception):
                    out = None  # step failure: token_id=-1 fallback downstream
                events.put_nowait(("out", out))
                if out is None or out.finish_reason is not None:
                    return

        drainer = asyncio.get_running_loop().create_task(drain_sink())
        self.scheduler.add(seq)
        self._wake.set()
        t0 = time.time()
        token, logp = None, None

        async def to_host(kb, vb, n):
            return await asyncio.to_thread(
                lambda: (np.ascontiguousarray(np.asarray(kb)[:, :n]),
                         np.ascontiguousarray(np.asarray(vb)[:, :n])))

        try:
            done = False
            while not done:
                kind, val = await events.get()
                if kind == "chunk":
                    # FIFO ordering guarantees every chunk event lands before
                    # the finish output that follows it in the queue
                    start, n, kb, vb = val
                    if mode is not None:
                        # ship the pow2-padded gather output unchanged (the
                        # compile-cache contract in ops/block_copy.py); the
                        # true block count rides the descriptor
                        desc = self.direct_transfer.offer(
                            mode, [kb, vb],
                            {"num_tokens": (start + n) * bs, "n": n,
                             "block_size": bs, "start_block": start})
                        yield KvDirectFrame(desc).to_wire()
                        continue
                    k, v = await to_host(kb, vb, n)
                    b = KvBundle(k=k, v=v, num_tokens=(start + n) * bs,
                                 block_size=bs, start_block=start)
                    yield KvChunkFrame(bundle=b).to_wire()
                elif val is None:
                    done = True
                else:
                    if val.token_ids:
                        token = val.token_ids[0]
                        logp = (val.log_probs or [None])[0]
                    if val.finish_reason is not None:
                        done = True
            if token is None:
                yield PrefillResponse(token_id=-1, logprob=None,
                                      bundle=None).to_wire()
                return
            total = (seq.prompt_len + bs - 1) // bs
            shipped = state["shipped"]
            bundle = None
            if total > shipped:
                n = total - shipped
                groups = layer_groups
                if groups and mode is None:
                    # layer-interleaved tail (docs/disagg.md): ONE gather,
                    # then host-stage + ship a layer group at a time — the
                    # wire/scatter of group g overlaps the device→host copy
                    # of group g+1, instead of serializing the full-depth
                    # bundle after prefill completes
                    kb = gather_blocks(self.k_cache,
                                       seq.block_table[shipped:total],
                                       block_size=bs)
                    vb = gather_blocks(self.v_cache,
                                       seq.block_table[shipped:total],
                                       block_size=bs)
                    L = kb.shape[0]
                    for g0, g1 in groups:
                        k, v = await to_host(kb[g0:g1], vb[g0:g1], n)
                        yield KvLayerFrame(KvBundle(
                            k=k, v=v, num_tokens=seq.prompt_len,
                            block_size=bs, start_block=shipped,
                            start_layer=g0, total_layers=L)).to_wire()
                elif groups and mode is not None:
                    # direct path: one offer per layer group — the decode
                    # side's pulls + layer scatters interleave the same way
                    kb = gather_blocks(self.k_cache,
                                       seq.block_table[shipped:total],
                                       block_size=bs)
                    vb = gather_blocks(self.v_cache,
                                       seq.block_table[shipped:total],
                                       block_size=bs)
                    L = kb.shape[0]
                    for g0, g1 in groups:
                        desc = self.direct_transfer.offer(
                            mode, [kb[g0:g1], vb[g0:g1]],
                            {"num_tokens": seq.prompt_len, "n": n,
                             "block_size": bs, "start_block": shipped,
                             "start_layer": g0, "total_layers": L})
                        yield KvDirectFrame(desc).to_wire()
                elif mode is not None:
                    kb = gather_blocks(self.k_cache,
                                       seq.block_table[shipped:total],
                                       block_size=bs)
                    vb = gather_blocks(self.v_cache,
                                       seq.block_table[shipped:total],
                                       block_size=bs)
                    desc = self.direct_transfer.offer(
                        mode, [kb, vb],
                        {"num_tokens": seq.prompt_len, "n": n,
                         "block_size": bs, "start_block": shipped})
                    yield KvDirectFrame(desc).to_wire()
                else:
                    bundle = await self._gather_bundle(
                        seq.block_table[shipped:total], seq.prompt_len,
                        shipped)
            yield PrefillResponse(token_id=token, logprob=logp,
                                  bundle=bundle).to_wire()
        finally:
            drainer.cancel()
            self.scheduler.abort(seq)
            self._wake.set()
            from dynamo_tpu.observability import get_tracer

            get_tracer().record("prefill.extract", ctx, start=t0,
                                end=time.time(), service="engine",
                                prompt_tokens=len(req.token_ids),
                                streamed=True, mode=mode or "host")

    def _kv_layer_groups(self, annotations):
        """Contiguous (start, end) layer ranges for the layer-interleaved
        tail transfer, or None for whole-bundle. Only when the decode peer
        advertised ``kv_layers`` (capability negotiation) AND this engine
        has splitting enabled AND the model is deep enough to split."""
        from dynamo_tpu.disagg.handlers import KV_LAYERS_ANNOTATION
        from dynamo_tpu.engine.cache import cache_shape

        g = getattr(self.args, "kv_transfer_layer_groups", 0) or 0
        if g <= 1 or KV_LAYERS_ANNOTATION not in (annotations or []):
            return None
        L = cache_shape(self.k_cache)[0]
        g = min(g, L)
        if g <= 1:
            return None
        base, rem = divmod(L, g)
        out, s = [], 0
        for i in range(g):
            e = s + base + (1 if i < rem else 0)
            out.append((s, e))
            s = e
        return out

    async def _gather_bundle(self, ids: list[int], num_tokens: int,
                             start_block: int):
        """Gather ``ids`` pages and bring them to host off the event loop."""
        from dynamo_tpu.disagg.protocols import KvBundle
        from dynamo_tpu.ops.block_copy import gather_blocks

        bs = self.args.block_size
        n = len(ids)
        kb = gather_blocks(self.k_cache, ids, block_size=bs)
        vb = gather_blocks(self.v_cache, ids, block_size=bs)
        # gather pads ids to a power of two; slice back host-side
        k, v = await asyncio.to_thread(
            lambda: (np.ascontiguousarray(np.asarray(kb)[:, :n]),
                     np.ascontiguousarray(np.asarray(vb)[:, :n])))
        return KvBundle(k=k, v=v, num_tokens=num_tokens, block_size=bs,
                        start_block=start_block)

    # ------------------------------------------------- decode-side injection

    def alloc_inject(self, n_blocks: int):
        """Allocate blocks for externally-computed KV, respecting admission
        limits (injection bypasses the waiting queue). None = can't place."""
        free_frac = self.pool.num_free_blocks / max(1, self.pool.num_blocks)
        if (len(self.scheduler.running) >= self.args.max_num_seqs
                or free_frac < self.args.watermark):
            return None
        return self.pool.allocate(n_blocks)

    def release_inject(self, ids) -> None:
        self.pool.release(ids)

    def check_bundle_dims(self, bundle) -> bool:
        from dynamo_tpu.engine.cache import cache_shape, packed_block_width
        L, slots, KV, hd = cache_shape(self.k_cache)
        if bundle.block_size != self.args.block_size:
            return False
        k = bundle.k
        # layer slices (docs/disagg.md): the bundle covers layers
        # [start_layer, start_layer + k.shape[0]) of a total_layers-deep
        # cache — depth must match OUR cache and the slice must fit
        tl = getattr(bundle, "total_layers", None)
        if tl is None:
            want_layers = L
        else:
            sl = getattr(bundle, "start_layer", 0) or 0
            if tl != L or sl < 0 or sl + k.shape[0] > L:
                return False
            want_layers = k.shape[0]
        if k.ndim == 3:  # packed quant bundle [nL, n, X]
            return (k.shape[0] == want_layers and k.dtype == np.uint8
                    and k.shape[2] == packed_block_width(
                        self.args.block_size, KV, hd))
        return k.shape[0] == want_layers and k.shape[3:] == (KV, hd)

    def scatter_chunk(self, ids, k: np.ndarray, v: np.ndarray,
                      start_layer=None) -> None:
        """Place received pages [L, n, bs, KV, hd] into device blocks
        ``ids``. ``start_layer`` set means k/v are a layer slice covering
        [start_layer, start_layer + k.shape[0]) only."""
        from dynamo_tpu.ops.block_copy import scatter_blocks

        bs = self.args.block_size
        self.k_cache = scatter_blocks(self.k_cache, ids, k, block_size=bs,
                                      start_layer=start_layer)
        self.v_cache = scatter_blocks(self.v_cache, ids, v, block_size=bs,
                                      start_layer=start_layer)

    async def generate_prefilled(self, req: PreprocessedRequest, token_id: int,
                                 logprob, ids, ctx=None
                                 ) -> AsyncIterator[LLMEngineOutput]:
        """Decode a request whose prompt KV is already scattered into ``ids``.

        Ownership of ``ids`` transfers to the sequence (released on finish).
        """
        from dynamo_tpu.observability import get_tracer

        self._ensure_loop()
        tracer = get_tracer()
        t0 = time.time()
        sink: asyncio.Queue = asyncio.Queue()
        seq = await self._new_seq(req, ctx, sink)
        if seq.guided_state is not None:
            # the prefill worker sampled this token under the same mask
            # (it compiles the same options); re-advance the local cursor —
            # in a thread, since a new DFA state costs an O(vocab) walk
            await asyncio.to_thread(seq.guided_state.advance, token_id)
        self.scheduler.add_prefilled(seq, ids)

        # the prefill worker's token is the stream's first output;
        # engine-side "TTFT" here is just the injection admission time
        # (the real prefill cost lives in the prefill worker's
        # prefill.extract span)
        first = LLMEngineOutput(token_ids=[token_id],
                                log_probs=[logprob]
                                if logprob is not None else None)
        self.scheduler.append_token(seq, token_id)
        t_first = time.time()
        tracer.record("engine.ttft", ctx, start=t0, end=t_first,
                      service="engine", prompt_tokens=len(req.token_ids),
                      injected=True)
        reason = self.scheduler.check_finish(seq, token_id)
        if reason is not None:
            first.finish_reason = reason
            self.scheduler.finish(seq, reason)
            yield first
            return
        yield first

        self._wake.set()
        n_tokens = 1
        try:
            while True:
                out = await sink.get()
                if out is None:
                    return
                if isinstance(out, Exception):
                    raise out  # chaos/step failure: surfaces as StreamError
                n_tokens += len(out.token_ids)
                yield out
                if out.finish_reason is not None:
                    return
        finally:
            tracer.record("engine.decode", ctx, start=t_first,
                          end=time.time(), service="engine",
                          tokens=n_tokens)

    async def generate_injected(self, req: PreprocessedRequest, prefill,
                                ctx=None) -> AsyncIterator[LLMEngineOutput]:
        """Decode a request whose prompt KV arrives as one whole KvBundle
        (the unpipelined path; the handler's streamed path uses
        alloc_inject/scatter_chunk/generate_prefilled directly).

        Falls back to a full local generate when the bundle can't be placed
        (allocation failure or block-size mismatch).
        """
        bundle = prefill.bundle
        if (bundle is None or prefill.token_id < 0
                or not self.check_bundle_dims(bundle)
                or bundle.start_block != 0):
            if bundle is not None and not self.check_bundle_dims(bundle):
                from dynamo_tpu.engine.cache import cache_shape
                logger.warning("KV bundle dims %s mismatch cache %s; local "
                               "prefill", bundle.k.shape,
                               cache_shape(self.k_cache))
            async for out in self.generate(req, ctx):
                yield out
            return

        self._ensure_loop()
        n = bundle.k.shape[1]
        ids = self.alloc_inject(n)
        if ids is None:  # memory pressure: recompute prefill locally
            async for out in self.generate(req, ctx):
                yield out
            return
        try:
            self.scatter_chunk(ids, bundle.k, bundle.v)
        except Exception:
            self.pool.release(ids)
            logger.exception("KV bundle scatter failed; local prefill")
            async for out in self.generate(req, ctx):
                yield out
            return
        async for out in self.generate_prefilled(req, prefill.token_id,
                                                 prefill.logprob, ids, ctx):
            yield out

    # ------------------------------------------------- KV-restore migration
    #
    # Stateful migration (docs/robustness.md): a migrated request's
    # recoverable prefix of (prompt ‖ emitted) is pulled from surviving
    # peers and attached HERE through the prefix cache — pool.register +
    # stored events, exactly like a KVBM onboard — so the subsequent
    # generate() prefix-matches it and recomputes only the tail. The
    # attach is charge-free by construction: prefix hits never advance
    # the QoS ledger (scheduler.commit_computed charges computed deltas
    # only), mirroring the disagg add_prefilled charge=False discipline.

    def restore_probe(self, req: PreprocessedRequest):
        """Salted TokenBlockSequence over the request's matchable full
        blocks — the hash chain restore pulls/attaches against. None when
        restore cannot apply (prefix caching off, or nothing matchable)."""
        from dynamo_tpu.tokens import TokenBlockSequence

        if not self.args.enable_prefix_caching:
            return None
        bs = self.args.block_size
        # never the whole prompt: at least one token must be computed
        # locally to produce logits (same rule as _prefix_match)
        matchable = (len(req.token_ids) - 1) // bs
        if matchable <= 0:
            return None
        return TokenBlockSequence.from_tokens(
            list(req.token_ids[: matchable * bs]), bs,
            Scheduler._salt_for(req))

    def resident_prefix_blocks(self, probe) -> int:
        """Leading blocks of ``probe`` recoverable here WITHOUT a peer
        pull: device prefix cache, or the G2 host tier that admission's
        synchronous onboard reads. G3/G4 do NOT count — disk only feeds a
        background promotion and G4 is a remote index, so treating them
        as resident would skip pulls the stream actually needed and then
        re-prefill anyway."""
        hashes = probe.sequence_hashes()
        in_host = (self.kvbm.host_resident(hashes)
                   if self.kvbm is not None else frozenset())
        n = 0
        for h in hashes:
            if self.pool.lookup(h) is None and h not in in_host:
                break
            n += 1
        return n

    def attach_restored(self, probe, start: int, blocks: list) -> int:
        """Scatter pulled peer blocks into fresh device blocks and REGISTER
        them (prefix cache + stored events), extending the contiguous
        restored prefix from block ``start``. ``blocks`` is an ordered
        [(seq_hash, k, v), ...] run; validation stops at the first torn
        entry (hash out of order or shape mismatch) — like PR 8's layer
        tears, a torn bundle is rejected, never half-scattered. Returns
        how many blocks were attached; 0 leaks nothing."""
        from dynamo_tpu.engine.cache import (
            cache_shape, is_quant_cache, packed_block_width,
        )

        if not blocks:
            return 0
        bs = self.args.block_size
        hashes = probe.sequence_hashes()
        L, _slots, KV, hd = cache_shape(self.k_cache)
        quant = is_quant_cache(self.k_cache)
        want_kv = (L, packed_block_width(bs, KV, hd)) if quant \
            else (L, bs, KV, hd)
        ks, vs = [], []
        for i, (h, k, v) in enumerate(blocks):
            pos = start + i
            if pos >= len(hashes) or h != hashes[pos]:
                logger.warning("restore bundle torn at block %d (hash "
                               "mismatch); keeping %d blocks", pos, len(ks))
                break
            ok = (tuple(k.shape) == want_kv and tuple(v.shape) == want_kv
                  and (k.dtype == np.uint8 if quant else True))
            if not ok:
                logger.warning("restore bundle block %d shape %s mismatches "
                               "cache %s; truncating", pos, k.shape, want_kv)
                break
            ks.append(k)
            vs.append(v)
        if not ks:
            return 0
        ids = self._scatter_register(probe, start, ks, vs)
        if ids is None:
            return 0  # memory pressure / torn scatter: recompute
        # park in the LRU (refcount 0): generate()'s prefix match re-
        # acquires them moments later; until then they are ordinary
        # evictable cache content, so a failed restore leaks nothing
        self.pool.release(ids)
        return len(ks)

    def _scatter_register(self, probe, start: int, ks: list, vs: list):
        """Shared attach protocol for externally-sourced block data
        (KVBM onboard + KV restore): allocate, scatter per-block k/v
        stacks into the cache, register each block's hashes, announce
        ONE chained stored event. Returns the allocated ids (refcount 1,
        caller decides ownership) or None with nothing leaked."""
        from dynamo_tpu.ops.block_copy import scatter_blocks

        bs = self.args.block_size
        ids = self.pool.allocate(len(ks))
        if ids is None:
            return None
        try:
            self.k_cache = scatter_blocks(self.k_cache, ids,
                                          np.stack(ks, 1), block_size=bs)
            self.v_cache = scatter_blocks(self.v_cache, ids,
                                          np.stack(vs, 1), block_size=bs)
        except Exception:
            self.pool.release(ids)
            logger.exception("block attach scatter failed")
            return None
        stored = []
        parent = (probe.blocks[start].parent_sequence_hash
                  if start < len(probe.blocks) else None)
        for i, bid in enumerate(ids):
            blk = probe.blocks[start + i]
            if self.pool.register(bid, blk.sequence_hash, blk.block_hash,
                                  blk.parent_sequence_hash):
                stored.append(StoredBlock(block_hash=blk.sequence_hash,
                                          tokens_hash=blk.block_hash))
        if stored and self.event_cb:  # this worker now owns the blocks
            self.event_cb(KvCacheEvent.stored(
                next(self._event_id), parent, stored))
        return ids

    async def export_blocks(self, hashes: list[int],
                            max_blocks: Optional[int] = None):
        """Serve a peer's KV-restore pull: yield (seq_hash, k, v) host
        arrays for the longest LEADING run of ``hashes`` recoverable here
        — device prefix cache first (pinned gather, same discipline as
        the offload path), then own G2/G3 tiers (kvbm.get_local; G4 is
        never touched — a deadline-bounded pull must not block on the
        object store). Stops at the first unrecoverable hash: restore
        attaches contiguous prefixes only."""
        from dynamo_tpu.ops.block_copy import gather_blocks

        bs = self.args.block_size
        budget = max_blocks if max_blocks is not None else len(hashes)
        run: list[tuple[int, int]] = []  # (hash, block_id) device run

        async def flush_run():
            if not run:
                return
            ids = [bid for _, bid in run]
            self.pool.acquire(ids)  # pin across the async gather
            try:
                kb = gather_blocks(self.k_cache, ids, block_size=bs)
                vb = gather_blocks(self.v_cache, ids, block_size=bs)

                def to_host():
                    kbh, vbh = np.asarray(kb), np.asarray(vb)
                    return [(np.ascontiguousarray(kbh[:, i]),
                             np.ascontiguousarray(vbh[:, i]))
                            for i in range(len(ids))]

                pairs = await asyncio.to_thread(to_host)
            finally:
                self.pool.release(ids)
            for (h, _bid), (k, v) in zip(run, pairs):
                yield h, k, v
            run.clear()

        served = 0
        for h in hashes:
            if served >= budget:
                break
            bid = self.pool.lookup(h)
            if bid is not None:
                run.append((h, bid))
                served += 1
                continue
            async for item in flush_run():
                yield item
            e = None
            if self.kvbm is not None:
                e = await asyncio.to_thread(self.kvbm.get_local, h)
            if e is None:
                break  # contiguity ends here
            served += 1
            yield h, e[0], e[1]
        async for item in flush_run():
            yield item

    def _hard_kill(self) -> None:
        """Chaos worker.kill: die like a SIGKILL. No sink resolution, no
        drain — just stop and tell the owner hooks (which exit the
        process, or kill serve handles + lease keepalive in-process)."""
        logger.warning("chaos: worker.kill fired — hard-dying with %d "
                       "running seqs", len(self.scheduler.running))
        self.killed = True
        self._closed = True
        for cb in list(self.on_kill):
            try:
                cb()
            except Exception:
                logger.exception("on_kill hook failed")

    def _ensure_loop(self) -> None:
        self._loop_ref = asyncio.get_running_loop()
        if self._task is None or self._task.done():
            self._task = self._loop_ref.create_task(self._run())

    async def close(self) -> None:
        self._closed = True
        self._wake.set()
        if self._task:
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if self._offload_tasks:
            await asyncio.gather(*list(self._offload_tasks),
                                 return_exceptions=True)
        if self.anomaly_profiler is not None:
            self.anomaly_profiler.close()  # stop a capture left open
        from dynamo_tpu.observability.flight import unregister_recorder
        unregister_recorder(self._flight_name)

    # ------------------------------------------------------------ main loop

    async def _run(self) -> None:
        logger.info("engine loop starting: %d blocks × %d tokens, tp=%d",
                    self.num_blocks, self.args.block_size, self.args.tp_size)
        while not self._closed:
            if not self.scheduler.has_work:
                self._wake.clear()
                await self._wake.wait()
                continue
            plan = self.scheduler.plan()
            chaos = _get_chaos()
            if (chaos is not None and not plan.empty
                    and chaos.should_error("worker.kill")):
                # seeded hard death mid-decode (SIGKILL-grade): stop the
                # loop NOW — no drain, no goodbye, in-flight sinks never
                # resolve. Streams break only when the lease TTL expires.
                self._hard_kill()
                return
            if (chaos is not None and not plan.empty
                    and chaos.should_error("engine.step")):
                # injected step crash: fail in-flight sequences with a
                # RETRYABLE stream error (a dead worker's streams migrate;
                # the chaos layer exercises exactly that path)
                logger.warning("chaos: engine.step error injected; failing "
                               "%d in-flight seqs",
                               len(self.scheduler.running))
                for s in list(self.scheduler.running):
                    self.scheduler.finish(s, FinishReason.ERROR)
                    s.sink.put_nowait(StreamError(
                        "chaos: injected engine step error"))
                continue
            if plan.empty:
                # memory-starved and nothing runnable: park until a BlockPool
                # release or a finishing sequence sets _wake (event-driven —
                # the old 5 ms poll burned a wakeup per tick under pressure).
                # The timeout is a safety net for edge signals that have no
                # hook (e.g. a context cancelled while we sleep).
                self._wake.clear()
                t0 = time.perf_counter()
                try:
                    await asyncio.wait_for(self._wake.wait(), timeout=0.05)
                except asyncio.TimeoutError:
                    pass
                # empty-step bubble: work exists but nothing could run —
                # the flight record carries how long the engine sat idle.
                # Rate-limited (same 10 ms guard as the mocker): _wake is
                # set by every arrival/cancel/offload, so a stall under
                # heavy ingress would otherwise flood the ring with
                # identical bubbles and evict the records explaining it
                now = time.monotonic()
                if now - self._last_empty_rec >= 0.01:
                    self._last_empty_rec = now
                    self._flight_record(
                        "empty", (time.perf_counter() - t0) * 1000,
                        decode_rows=0, prefill_chunks=0, chunk_tokens=0)
                continue
            try:
                await self._execute(plan)
            except Exception:
                logger.exception("engine step failed; failing in-flight seqs")
                for s in list(self.scheduler.running):
                    self.scheduler.finish(s, FinishReason.ERROR)
                    s.sink.put_nowait(LLMEngineOutput(
                        finish_reason=FinishReason.ERROR, text="engine step failed"))
            self.steps += 1
            if self.metrics_cb:
                self.metrics_cb(self._metrics())
            # let request ingress / cancellation run
            await asyncio.sleep(0)

    async def _execute(self, plan: StepPlan) -> None:
        # env-gated jax.profiler correlation (DYN_JAX_PROFILER=1): device
        # traces carry the serving phase names alongside request spans
        from dynamo_tpu.observability.profiler import annotate

        if not plan.prefill and plan.decode and self._can_pipeline(plan.decode):
            with annotate("dynamo.decode_pipeline"):
                if await self._run_decode_pipelined(plan.decode):
                    return
        if plan.empty:
            return
        # decode-only plans may take the burst/spec fast paths (K tokens or
        # a draft+verify round per dispatch) before falling back to the one
        # packed launch below
        if not plan.prefill and plan.decode:
            if await self._run_decode_fast(plan.decode):
                return
        # one packed launch for the whole plan — prefill chunks and
        # decode rows together (docs/performance.md ragged step). ONE
        # flight record per plan: the record owns the plan's starvation
        # count, QoS mix, and padded-token accounting.
        t0 = time.perf_counter()
        n_tok = sum(w.chunk for w in plan.prefill) + len(plan.decode)
        with annotate("dynamo.ragged_step"):
            padded = await self._run_ragged(plan)
        wall = (time.perf_counter() - t0) * 1000
        if not plan.prefill and plan.decode:
            # plain decode step wall: the spec governor's cost baseline
            self._decode_step_ms = (
                wall if self._decode_step_ms is None
                else 0.8 * self._decode_step_ms + 0.2 * wall)
        self.step_trace.append((
            "ragged", len(plan.prefill) + len(plan.decode), n_tok,
            wall, padded))
        self._flight_record(
            "ragged", wall, decode_rows=len(plan.decode),
            prefill_chunks=len(plan.prefill),
            chunk_tokens=sum(w.chunk for w in plan.prefill),
            padded=padded, dispatch_ms=self._last_dispatch_ms,
            qos_mix=self._plan_qos_mix(plan),
            constrained=self._constrained_count(
                plan.decode + [w.seq for w in plan.prefill]),
            decode_seqs=plan.decode,
            prefill_seqs=[w.seq for w in plan.prefill])

    def step_trace_summary(self) -> dict:
        """Aggregate the timing ring: per kind, steps / seqs / tokens /
        total+mean wall — the first thing to read when e2e throughput is
        far below the kernel ceiling."""
        agg: dict[str, list] = {}
        for kind, n, toks, ms, *rest in self.step_trace:
            a = agg.setdefault(kind, [0, 0, 0, 0.0, 0])
            a[0] += 1
            a[1] += n
            a[2] += toks
            a[3] += ms
            a[4] += rest[0] if rest else 0  # padded tokens (ragged entries)
        return {k: {"steps": a[0], "seqs": a[1], "tokens": a[2],
                    "total_ms": round(a[3], 1),
                    "mean_ms": round(a[3] / a[0], 1),
                    "padded_tokens": a[4]}
                for k, a in agg.items()}

    # --------------------------------------------------- flight recording

    def _note_compile(self, kind: str, sig: tuple, seconds: float) -> None:
        """A serving dispatch just traced a NEW jit signature: count it,
        time it, stage it for the step's flight record, and WARN when it
        happened in steady state (the silent latency cliff)."""
        self.compile_events[kind] = self.compile_events.get(kind, 0) + 1
        self.compile_seconds[kind] = (self.compile_seconds.get(kind, 0.0)
                                      + seconds)
        try:
            from dynamo_tpu.observability import get_tracer
            get_tracer().metrics.histogram(
                "compile_seconds",
                "seconds spent tracing/compiling post-warmup jit "
                "signatures").observe(seconds)
        except Exception:
            pass  # metrics must never fail a step
        self._last_compile = (kind, sig, seconds)
        # SAME steady signal as the record's compile-steady tag (the
        # recorder's count) so the WARNING and the tag never desync; with
        # recording disabled, executed steps are the fallback proxy
        steady = (self.flight.steady() if self.flight.enabled
                  else self.steps >= self.flight.steady_after)
        if steady:
            logger.warning(
                "steady-state compile: signature %s traced in %.2fs at "
                "step %d (warmup did not cover this shape)",
                (kind,) + tuple(sig), seconds, self.steps)

    def kv_tier_occupancy(self) -> dict:
        """G1–G4 occupancy for /metrics gauges, flight records, and
        ``dynctl top``: ``{tier: {"blocks": n, "bytes": n}}``. G1 is the
        device paged cache (active blocks); G2/G3/G4 come from the KVBM
        hierarchy when configured (zeros otherwise — the series exist
        either way, so dashboards can wire against an unconfigured tier)."""
        if self._kv_block_nbytes is None:
            try:
                import jax
                leaves = jax.tree_util.tree_leaves(
                    (self.k_cache, self.v_cache))
                total = sum(int(x.nbytes) for x in leaves)
                self._kv_block_nbytes = total // max(1, self.num_blocks)
            except Exception:
                self._kv_block_nbytes = 0
        g1 = self.pool.num_active_blocks
        out = {"g1": {"blocks": g1,
                      "bytes": g1 * (self._kv_block_nbytes or 0)}}
        if self.kvbm is not None:
            s = self.kvbm.stats()
            out["g2"] = {"blocks": s["host_blocks"],
                         "bytes": s["host_bytes"]}
            out["g3"] = {"blocks": s["disk_blocks"],
                         "bytes": s["disk_bytes"]}
            out["g4"] = {"blocks": s["remote_blocks"],
                         "bytes": s["remote_bytes"]}
        else:
            for tier in ("g2", "g3", "g4"):
                out[tier] = {"blocks": 0, "bytes": 0}
        return out

    def _flight_record(self, kind: str, wall_ms: float, decode_rows: int,
                       prefill_chunks: int, chunk_tokens: int,
                       padded: int = 0, dispatch_ms: float = 0.0,
                       qos_mix: Optional[dict] = None,
                       starved: Optional[int] = None,
                       constrained: int = 0,
                       decode_seqs=None, prefill_seqs=None) -> None:
        """Append one flight record for an executed step: snapshot queue
        depths + tier occupancy, difference the cumulative preempt/swap
        totals into per-step deltas, attach a compile staged by
        ``_note_compile`` during this step's dispatch, stamp the
        step↔request-id linkage the attribution join needs, and feed the
        anomaly-triggered profiler."""
        fb = self.ragged_fallback_reason
        if fb is not None:
            # every executed step on a degraded attention path counts —
            # the counter runs even with the flight recorder disabled
            self.ragged_fallback_total[fb] = (
                self.ragged_fallback_total.get(fb, 0) + 1)
        if not self.flight.enabled:
            return
        sched = self.scheduler
        cur = {"ps": sched.preempt_swap_total,
               "pr": sched.preempt_recompute_total,
               "so": self.swap_out_blocks, "si": self.swap_in_blocks}
        last = self._flight_last
        delta = {k: cur[k] - last.get(k, 0) for k in cur}
        self._flight_last = cur
        compile_s, compile_sig = 0.0, ""
        if self._last_compile is not None:
            ck, cs, csec = self._last_compile
            compile_s = csec
            compile_sig = ":".join(str(x) for x in (ck,) + tuple(cs))
            self._last_compile = None
        now = time.monotonic()
        if now - self._flight_tiers_t > 0.05:
            self._flight_tiers = {
                t: v["blocks"] for t, v in self.kv_tier_occupancy().items()}
            self._flight_tiers_t = now
        tiers = self._flight_tiers
        rec = self.flight.record(
            kind, wall_ms,
            dispatch_ms=dispatch_ms,
            decode_rows=decode_rows, prefill_chunks=prefill_chunks,
            chunk_tokens=chunk_tokens, padded_tokens=padded,
            compile_s=compile_s, compile_sig=compile_sig,
            preempt_swap=delta["ps"], preempt_recompute=delta["pr"],
            swap_out_blocks=delta["so"], swap_in_blocks=delta["si"],
            waiting=sched.num_waiting(), swapped=len(sched.swapped),
            running=len(sched.running),
            starved_decode=(sched.last_starved_decode
                            if starved is None else starved),
            constrained_rows=constrained,
            kv_tiers=tiers, qos_mix=qos_mix or {},
            decode_ids=self._ctx_ids(decode_seqs),
            prefill_ids=self._ctx_ids(prefill_seqs),
            starved_ids=(list(sched.last_starved_ids)
                         if starved is None else []))
        if rec is not None and fb is not None:
            rec.tags.append("ragged_fallback:" + fb)
        if self.anomaly_profiler is not None:
            self.anomaly_profiler.on_record(rec)

    @staticmethod
    def _ctx_ids(seqs) -> list:
        """Request ids (Context ids — what traces and attribution key on)
        of the step's sequences; context-less seqs contribute nothing."""
        if not seqs:
            return []
        out = []
        for s in seqs:
            rid = getattr(s.ctx, "id", None)
            if rid:
                out.append(rid)
        return out

    @staticmethod
    def _qos_mix_of(seqs) -> dict:
        mix: dict[str, int] = {}
        for s in seqs:
            mix[s.priority] = mix.get(s.priority, 0) + 1
        return mix

    @staticmethod
    def _constrained_count(seqs) -> int:
        return sum(1 for s in seqs if s.guided_state is not None)

    def _plan_qos_mix(self, plan: StepPlan) -> dict:
        return self._qos_mix_of(
            plan.decode + [w.seq for w in plan.prefill])

    # ------------------------------------------------------- bucket warmup

    async def warmup(self, seq_lens: Optional[list] = None,
                     prefill_batches: Optional[list] = None) -> dict:
        """AOT precompile of the ragged token-bucket signatures, so the
        first REAL request never eats an XLA compile — first-compile is the
        TTFT p95-vs-p50 cliff this attacks.

        The ragged step's whole signature space IS the token-bucket list
        (R, W, and the chunk grid derive statically from T), so warmup is a
        handful of traces instead of the old (chunk × batch × width)
        bucketed lattice. ``seq_lens`` / ``prefill_batches`` are accepted
        for API compatibility but choose nothing — the table width never
        enters a ragged signature. Dummy writes land in the reserved NULL
        block, whose contents are garbage by design. Must run BEFORE
        serving traffic (the dummy calls ride the same donated cache chain
        as real steps). Returns a report listing each compiled signature
        exactly once.
        """
        if self._multihost:
            # NOT silent: warmup_skipped feeds WorkerStats.warmed_up, so
            # the operator's readiness gate (deploy/operator.py) stops
            # counting this worker as warm until its first real step lands
            # — a cold multi-host worker must not absorb autoscale traffic
            # projections while it pays the compile cliff.
            logger.warning("bucket warmup skipped under multi-host (dummy "
                           "steps are not in the leader's broadcast "
                           "replay); worker reports warmed_up=false until "
                           "its first served step")
            self.warmup_skipped = True
            return {"skipped": "multihost"}
        if self.scheduler.has_work:
            # the dummy dispatches run in a worker thread and reassign the
            # donated cache chain; racing a live engine step would hand XLA
            # an already-donated buffer and fail every in-flight sequence
            raise RuntimeError(
                "bucket warmup must run before serving traffic (sequences "
                "are already scheduled)")
        args = self.args
        t_start = time.perf_counter()

        def run_ragged():
            import jax.numpy as jnp

            from dynamo_tpu.engine.model import ragged_grid_shape

            report: dict = {"ragged": [], "sample": []}
            sampled: set = set()
            for T in args.ragged_token_buckets:
                R = args.ragged_rows(T)
                W = args.max_blocks_per_seq
                C, _ = ragged_grid_shape(T)
                ints5 = np.zeros((5, T), np.int32)
                ints5[3] = C
                rows3 = np.zeros((R, 3), np.int32)
                rows3[0] = (0, 1, 1)  # one real row attending a NULL slot
                bt = np.full((R, W), NULL_BLOCK, np.int32)
                gr = np.zeros((C,), np.int32)
                if self.pp_fn is not None:
                    # pp: one packed microbatch stack per token bucket —
                    # the signature is (T, M) with M fixed at pp_size
                    Mmb = self._pp
                    logits, self.k_cache, self.v_cache = self.pp_fn(
                        self.params,
                        jnp.asarray(np.broadcast_to(
                            ints5, (Mmb, 5, T)).copy()),
                        jnp.asarray(np.broadcast_to(
                            rows3, (Mmb, R, 3)).copy()),
                        jnp.asarray(np.broadcast_to(gr, (Mmb, C)).copy()),
                        jnp.asarray(np.broadcast_to(
                            bt, (Mmb, R, W)).copy()),
                        self.k_cache, self.v_cache)
                    logits = logits[0]
                    self.compiled_signatures.add(("pp", T, Mmb))
                    report["ragged"].append(("pp", T, R, W))
                else:
                    # both variants: the mixed step and the pipelined
                    # decode-only step
                    for kind, fn in (("ragged", self.ragged_fn),
                                     ("ragged_dec", self.ragged_dec_fn)):
                        logits, self.k_cache, self.v_cache = fn(
                            self.params, jnp.asarray(ints5),
                            jnp.asarray(rows3), jnp.asarray(gr),
                            jnp.asarray(bt), self.k_cache, self.v_cache)
                        self.compiled_signatures.add((kind, T))
                        report["ragged"].append((kind, T, R, W))
                if R not in sampled:
                    sampled.add(R)
                    toks, _ = self._sampling.sample_jit(
                        logits, np.zeros((R,), np.float32),
                        np.zeros((R,), np.int32), np.ones((R,), np.float32),
                        self._sampling.make_keys([0] * R, [0] * R))
                    np.asarray(toks)
                    report["sample"].append(R)
            return report

        report = await asyncio.to_thread(run_ragged)
        report["seconds"] = round(time.perf_counter() - t_start, 2)
        logger.info("ragged warmup: %d token-bucket signatures in %.1fs",
                    len(report["ragged"]), report["seconds"])
        return report

    # ------------------------------------------------------------- prefill

    def _mm_arrays(self, seq, start: int, end: int, S: int):
        """(mm_vec [1,S,D] f32, mm_mask [1,S] bool) for the chunk, or None
        when no multimodal segment overlaps [start, end)."""
        segs = seq.req.mm_embeds or []
        D = self.cfg.hidden_size
        vec = None
        mask = None
        for seg in segs:
            s0 = int(seg.get("start", 0))
            rows = seg["embeds"]
            for j, row in enumerate(rows):
                p = s0 + j
                if start <= p < end:
                    if vec is None:
                        vec = np.zeros((1, S, D), np.float32)
                        mask = np.zeros((1, S), bool)
                    vec[0, p - start, :len(row)] = row
                    mask[0, p - start] = True
        return (vec, mask) if vec is not None else None

    # -------------------------------------------------------- ragged step

    def _get_ragged_mm_fn(self):
        if self._ragged_mm_fn is None:
            from dynamo_tpu.engine import model as M

            self._ragged_mm_fn = M.make_ragged_step_fn(
                self.cfg, self.args.block_size, self.mesh,
                use_pallas=self.args.use_pallas_attention,
                replicate_logits=self._multihost,
                kv_quant=self._kv_quant, mm=True)
        return self._ragged_mm_fn

    def _get_verify_masked_fn(self):
        if self._verify_masked_fn is None:
            from dynamo_tpu.engine import model as M

            self._verify_masked_fn = M.make_ragged_verify_fn(
                self.cfg, self.args.block_size, self.mesh,
                replicate_outputs=self._multihost,
                kv_quant=self._kv_quant, masked=True)
        return self._verify_masked_fn

    async def _run_ragged(self, plan: StepPlan) -> int:
        """Execute the WHOLE plan — decode rows and prefill chunks — as one
        packed ragged launch (ops/ragged_attention.py; docs/performance.md).

        Every row's tokens pack consecutively into a [T_bucket] batch with
        per-row (q_start, q_len, kv_len) metadata; nothing pads to a
        chunk/batch/width bucket, so the only waste is the tail of the one
        token bucket (returned, for the step trace / padded-tokens metric).
        Under pipeline parallelism the plan splits into M packed ragged
        microbatches instead (_run_ragged_pp).
        """
        if self.pp_fn is not None:
            return await self._run_ragged_pp(plan)
        import jax.numpy as jnp

        from dynamo_tpu.engine.model import ragged_grid_shape

        args = self.args
        bs = args.block_size
        works = plan.prefill
        total = len(plan.decode) + sum(w.chunk for w in works)
        T = args.bucket_ragged_tokens(total)
        R = args.ragged_rows(T)
        W = args.max_blocks_per_seq
        C, S_C = ragged_grid_shape(T)
        self.param_reads += 1
        self.padded_tokens_total += T - total

        # ints5: tokens / positions / slot_map / grid_row / grid_col —
        # grid_row defaults to the dump tile C (decode + padding tokens)
        ints5 = np.zeros((5, T), np.int32)
        ints5[3] = C
        rows3 = np.zeros((R, 3), np.int32)  # q_start/q_len/kv_len; 0 = pad
        grid_rows = np.zeros((C,), np.int32)
        bt = np.full((R, W), NULL_BLOCK, np.int32)
        mm_vec = mm_mask = None
        #: (seq, samples?) in row order — decode rows first, then chunks
        rows = [(s, True, None) for s in plan.decode]
        rows += [(w.seq, w.sample, w) for w in works]
        t = 0
        tile = 0
        for i, (seq, _sample, w) in enumerate(rows):
            if w is None:  # decode row: one token, the sequence's newest
                start, chunk = len(seq.tokens) - 1, 1
            else:
                start, chunk = w.start, w.chunk
            end = start + chunk
            ints5[0, t:t + chunk] = seq.tokens[start:end]
            ints5[1, t:t + chunk] = np.arange(start, end)
            for j, pos in enumerate(range(start, end)):
                ints5[2, t + j] = seq.block_table[pos // bs] * bs + pos % bs
            if chunk > 1:
                # chunk grid tiling: ceil(chunk / S_C) tiles of this row
                # (1-token chunks ride the decode sub-call instead)
                for off in range(0, chunk, S_C):
                    width = min(S_C, chunk - off)
                    grid_rows[tile] = i
                    ints5[3, t + off:t + off + width] = tile
                    ints5[4, t + off:t + off + width] = np.arange(width)
                    tile += 1
            rows3[i] = (t, chunk, end)
            n = min(len(seq.block_table), W)
            bt[i, :n] = seq.block_table[:n]
            if w is not None:
                mm = self._mm_arrays(seq, start, end, chunk)
                if mm is not None:
                    if mm_vec is None:
                        mm_vec = np.zeros((T, self.cfg.hidden_size),
                                          np.float32)
                        mm_mask = np.zeros((T,), bool)
                    mm_vec[t:t + chunk] = mm[0][0]
                    mm_mask[t:t + chunk] = mm[1][0]
            t += chunk
        assert tile <= C, f"chunk grid overflow: {tile} > {C}"

        operands = {"ints5": ints5, "rows3": rows3, "grid_rows": grid_rows,
                    "block_tables": bt}
        if mm_vec is not None:
            operands["mm_vec"], operands["mm_mask"] = mm_vec, mm_mask
            kind, fn = "ragged_mm", self._get_ragged_mm_fn()
        elif works:
            kind, fn = "ragged", self.ragged_fn
        else:
            # decode-only plan that bypassed the pipelined loop (logprobs,
            # host-oracle guided fallbacks, penalties, swapped/waiting
            # work pending): the no-chunk-grid variant
            kind, fn = "ragged_dec", self.ragged_dec_fn
        new_sig = (kind, T) not in self.compiled_signatures
        self.compiled_signatures.add((kind, T))
        self._broadcast(kind, **operands)
        t0d = time.perf_counter()
        logits, self.k_cache, self.v_cache = fn(
            self.params,
            *(self._put_batch(k, v) for k, v in operands.items()),
            self.k_cache, self.v_cache)
        self._last_dispatch_ms = (time.perf_counter() - t0d) * 1000
        if new_sig:
            self._note_compile(kind, (T,), time.perf_counter() - t0d)

        # commit BEFORE sampling, exactly like the bucketed steps: chunk
        # progress (and disagg block shipping) must never wait on the
        # sampler's host round trip
        for w in works:
            seq, end = w.seq, w.start + w.chunk
            self.scheduler.commit_computed(seq, end)
            if seq.progress_cb is not None:
                try:
                    seq.progress_cb(end)
                except Exception:
                    logger.exception("prefill progress callback failed; "
                                     "disabling chunk shipping for %s",
                                     seq.request_id)
                    seq.progress_cb = None
        for s in plan.decode:
            self.scheduler.commit_computed(s, len(s.tokens))

        sample_rows = [(i, seq) for i, (seq, smp, _w) in enumerate(rows)
                       if smp]
        if not sample_rows:
            # every row was a mid-prompt chunk: logits unused, sync to pace
            await asyncio.to_thread(lambda: logits.block_until_ready())
            return T - total
        idx = [i for i, _ in sample_rows]
        if idx == list(range(len(rows))):
            # common case: every row samples — _sample tolerates the
            # padded R >= len(rows), no gather needed
            sel = logits
        else:
            Bp = args.bucket_batch(len(idx))
            sel = logits[jnp.asarray(idx + [idx[0]] * (Bp - len(idx)),
                                     jnp.int32)]
        seqs = [s for _, s in sample_rows]
        toks, logps, tops = await self._sample(seqs, sel)
        for j, (_i, seq) in enumerate(sample_rows):
            self._deliver(seq, int(toks[j]), float(logps[j]), tops.get(j))
        return T - total

    async def _run_ragged_pp(self, plan: StepPlan) -> int:
        """The pipeline-parallel ragged step: the plan's rows split into
        M = pp_size packed ragged microbatches (longest-first greedy into
        the lightest bin, so the GPipe ticks stay balanced), each bin laid
        out exactly like the single-bin packed launch. The compiled
        signature is (T, M) — T covers the HEAVIEST bin, M is fixed — so
        pp serving warms the same token-bucket family as everything else.
        """
        import jax.numpy as jnp

        from dynamo_tpu.engine.model import ragged_grid_shape

        args = self.args
        bs = args.block_size
        works = plan.prefill
        Mmb = self._pp
        rows_all = [(s, True, None) for s in plan.decode]
        rows_all += [(w.seq, w.sample, w) for w in works]

        def ntok(row):
            return 1 if row[2] is None else row[2].chunk

        bins: list[list] = [[] for _ in range(Mmb)]
        loads = [0] * Mmb
        for row in sorted(rows_all, key=ntok, reverse=True):
            m = loads.index(min(loads))
            bins[m].append(row)
            loads[m] += ntok(row)
        total = sum(loads)
        T = args.bucket_ragged_tokens(max(1, max(loads)))
        R = args.ragged_rows(T)
        W = args.max_blocks_per_seq
        C, S_C = ragged_grid_shape(T)
        self.param_reads += 1
        self.padded_tokens_total += Mmb * T - total

        ints5 = np.zeros((Mmb, 5, T), np.int32)
        ints5[:, 3] = C
        rows3 = np.zeros((Mmb, R, 3), np.int32)
        grid_rows = np.zeros((Mmb, C), np.int32)
        bt = np.full((Mmb, R, W), NULL_BLOCK, np.int32)
        #: (bin, row-in-bin, seq) for every sampling row, bin pack order
        sample_rows = []
        for m, rows in enumerate(bins):
            t = 0
            tile = 0
            for i, (seq, sample, w) in enumerate(rows):
                if w is None:
                    start, chunk = len(seq.tokens) - 1, 1
                else:
                    start, chunk = w.start, w.chunk
                    if seq.req.mm_embeds:
                        # backstop only — _new_seq refuses mm requests at
                        # admission under pp
                        raise RuntimeError(
                            "multimodal prefill is not supported under "
                            "pipeline parallelism")
                end = start + chunk
                ints5[m, 0, t:t + chunk] = seq.tokens[start:end]
                ints5[m, 1, t:t + chunk] = np.arange(start, end)
                for j, pos in enumerate(range(start, end)):
                    ints5[m, 2, t + j] = (seq.block_table[pos // bs] * bs
                                          + pos % bs)
                if chunk > 1:
                    for off in range(0, chunk, S_C):
                        width = min(S_C, chunk - off)
                        grid_rows[m, tile] = i
                        ints5[m, 3, t + off:t + off + width] = tile
                        ints5[m, 4, t + off:t + off + width] = (
                            np.arange(width))
                        tile += 1
                rows3[m, i] = (t, chunk, end)
                n = min(len(seq.block_table), W)
                bt[m, i, :n] = seq.block_table[:n]
                if sample:
                    sample_rows.append((m, i, seq))
                t += chunk
            assert tile <= C, f"chunk grid overflow: {tile} > {C}"

        operands = {"ints5": ints5, "rows3": rows3, "grid_rows": grid_rows,
                    "block_tables": bt}
        new_sig = ("pp", T, Mmb) not in self.compiled_signatures
        self.compiled_signatures.add(("pp", T, Mmb))
        self._broadcast("pp", **operands)
        t0d = time.perf_counter()
        logits, self.k_cache, self.v_cache = self.pp_fn(
            self.params,
            *(self._put_batch(k, v) for k, v in operands.items()),
            self.k_cache, self.v_cache)
        self._last_dispatch_ms = (time.perf_counter() - t0d) * 1000
        if new_sig:
            self._note_compile("pp", (T, Mmb), time.perf_counter() - t0d)

        # commit BEFORE sampling, exactly like the single-bin launch
        for w in works:
            seq, end = w.seq, w.start + w.chunk
            self.scheduler.commit_computed(seq, end)
            if seq.progress_cb is not None:
                try:
                    seq.progress_cb(end)
                except Exception:
                    logger.exception("prefill progress callback failed; "
                                     "disabling chunk shipping for %s",
                                     seq.request_id)
                    seq.progress_cb = None
        for s in plan.decode:
            self.scheduler.commit_computed(s, len(s.tokens))

        if not sample_rows:
            await asyncio.to_thread(lambda: logits.block_until_ready())
            return Mmb * T - total
        # logits land [M, R, V]: flatten and gather the sampling rows,
        # padded to a batch bucket so the sampling jit sees bounded shapes
        idx = [m * R + i for m, i, _ in sample_rows]
        Bp = args.bucket_batch(len(idx))
        flat = logits.reshape(Mmb * R, logits.shape[-1])
        sel = flat[jnp.asarray(idx + [idx[0]] * (Bp - len(idx)), jnp.int32)]
        seqs = [s for _m, _i, s in sample_rows]
        toks, logps, tops = await self._sample(seqs, sel)
        for j, (_m, _i, seq) in enumerate(sample_rows):
            self._deliver(seq, int(toks[j]), float(logps[j]), tops.get(j))
        return Mmb * T - total

    # -------------------------------------------------------------- decode

    # ---------------------------------------------- speculative decoding

    @staticmethod
    def _draft_tokens(s, k: int) -> list[int]:
        """Prompt-lookup draft: match the trailing 3- or 2-gram earlier in
        the sequence and propose the tokens that followed it.

        O(new tokens) per call: ``s.ngram_pos`` maps each n-gram to the END
        position of its newest occurrence, extended incrementally — a full
        backward history scan per decode step would be O(n²) Python work on
        the event loop over a long generation. The current trailing gram's
        own end is deliberately left unindexed until the sequence grows past
        it, so a lookup never matches itself.
        """
        tokens = s.tokens
        n_tok = len(tokens)
        idx = s.ngram_pos
        for e in range(max(s.ngram_indexed + 1, 2), n_tok):  # end-exclusive
            if e >= 2:
                idx[(tokens[e - 2], tokens[e - 1])] = e
            if e >= 3:
                idx[(tokens[e - 3], tokens[e - 2], tokens[e - 1])] = e
        s.ngram_indexed = max(s.ngram_indexed, n_tok - 1)
        for n in (3, 2):
            if n_tok <= n:
                continue
            e = idx.get(tuple(tokens[-n:]))
            if e is not None:
                cont = tokens[e:e + k]
                if cont:
                    return cont
        return []

    def _prealloc_blocks(self, seqs: list[SeqState], extra: int) -> bool:
        """All-or-nothing block preallocation for fused decode paths — a
        partial extension left behind would deepen the memory pressure that
        made it fail (shared by the burst and speculative paths)."""
        extended: list = []
        for s in seqs:
            before = len(s.block_table)
            if not self.scheduler._ensure_blocks(s, len(s.tokens) + extra):
                for s2, b2 in extended:
                    self.pool.release(s2.block_table[b2:])
                    del s2.block_table[b2:]
                return False
            if len(s.block_table) > before:
                extended.append((s, before))
        return True

    async def _run_draft_model(self, seqs: list[SeqState],
                               K: int) -> list[list[int]]:
        """Layer-skip draft dispatch: K greedy tokens per row from the
        first speculative_draft_layers layers (model.make_draft_fn). Draft
        KV lands in the tokens' real slots — blocks are already
        preallocated by the caller."""
        args = self.args
        # ragged-family signature, like the multi burst: row bucket from
        # the token bucket, static table width
        B = args.ragged_rows(args.bucket_ragged_tokens(len(seqs)))
        W = args.max_blocks_per_seq

        last_tokens = np.zeros((B,), np.int32)
        positions = np.zeros((B,), np.int32)
        bt = np.full((B, W), NULL_BLOCK, np.int32)
        kv_lens = np.zeros((B,), np.int32)
        for i, s in enumerate(seqs):
            last_tokens[i] = s.tokens[-1]
            positions[i] = len(s.tokens) - 1
            n = min(len(s.block_table), W)
            bt[i, :n] = s.block_table[:n]
            kv_lens[i] = len(s.tokens)

        ints = np.stack([last_tokens, positions, kv_lens], axis=1)
        self.compiled_signatures.add(("draft", B))
        self._broadcast("draft", ints=ints, block_tables=bt)
        toks, self.k_cache, self.v_cache = self.draft_fn(
            self.params, self._put_batch("ints", ints),
            self._put_batch("block_tables", bt),
            self.k_cache, self.v_cache)
        # draft forwards read draft_layers/num_layers of the weights
        self.param_reads += (K * args.speculative_draft_layers
                             / self.cfg.num_layers)
        toks = await asyncio.to_thread(lambda: np.asarray(toks))
        return [toks[:, i].tolist() for i in range(len(seqs))]

    async def _run_spec_decode(self, seqs: list[SeqState]) -> bool:
        """Draft-and-verify: one forward over [last_token, draft...] per seq
        accepts the longest greedy-matching draft prefix plus one corrected
        token — emitting 1..K+1 tokens per dispatch with EXACTLY the tokens
        plain greedy decode would produce. Returns False (fall back) when no
        seq drafts anything or block preallocation fails."""
        args = self.args
        K = args.speculative_tokens
        t0 = time.perf_counter()
        if self.draft_fn is not None:
            # the draft model writes KV into the draft slots, so blocks
            # must exist BEFORE drafting
            if not self._prealloc_blocks(seqs, K):
                return False
            drafts = await self._run_draft_model(seqs, K)
        else:
            drafts = [self._draft_tokens(s, K) for s in seqs]
            if not any(drafts):
                return False
            if not self._prealloc_blocks(seqs, K):
                return False
        ok = await self._verify_and_commit(seqs, drafts)
        if ok:
            # measured spec round (draft + verify + host round trip): the
            # governor's cost re-baseline (_spec_dispatch_cost)
            wall = (time.perf_counter() - t0) * 1000
            self._spec_round_ms = (
                wall if self._spec_round_ms is None
                else 0.8 * self._spec_round_ms + 0.2 * wall)
        return ok

    async def _verify_and_commit(self, seqs: list[SeqState],
                                 drafts: list[list[int]]) -> bool:
        """Verify ON the packed ragged layout: each seq is one ragged row
        with q_len = draft+1, so verify shares the serving step's
        token-bucket signature family instead of its own [B, S, W]
        lattice. Every verify row is a chunk (q_len > 1) occupying
        ceil(S / tile) chunk-grid tiles; the token bucket is chosen as the
        smallest that holds both the packed tokens AND the needed tiles,
        dispatching in groups when even the largest bucket cannot."""
        from dynamo_tpu.engine.model import ragged_grid_shape

        args = self.args
        K = args.speculative_tokens
        S = 1 + K
        bs = args.block_size

        def bucket_for(n: int):
            # smallest token bucket with n*S tokens AND n chunk rows' tiles
            for cand in args.ragged_token_buckets:
                C, S_C = ragged_grid_shape(cand)
                if cand >= n * S and n * -(-S // S_C) <= C:
                    return cand
            return None

        T_all = bucket_for(len(seqs))
        if T_all is not None:
            groups = [list(range(len(seqs)))]
        else:
            Tmax = args.ragged_token_buckets[-1]
            C, S_C = ragged_grid_shape(Tmax)
            cap = max(1, min(C // -(-S // S_C), Tmax // S))
            groups = [list(range(i, min(i + cap, len(seqs))))
                      for i in range(0, len(seqs), cap)]

        total_emitted = 0
        for grp in groups:
            n = len(grp)
            T = T_all if T_all is not None else bucket_for(n)
            R = args.ragged_rows(T)
            W = args.max_blocks_per_seq
            C, S_C = ragged_grid_shape(T)
            ints5 = np.zeros((5, T), np.int32)
            ints5[3] = C  # padding tokens: grid dump tile
            rows3 = np.zeros((R, 3), np.int32)
            grid_rows = np.zeros((C,), np.int32)
            bt = np.full((R, W), NULL_BLOCK, np.int32)
            t = 0
            tile = 0
            for i, gi in enumerate(grp):
                s = seqs[gi]
                d = drafts[gi]
                row = [s.tokens[-1]] + d + [0] * (K - len(d))
                base = len(s.tokens) - 1
                ints5[0, t:t + S] = row
                ints5[1, t:t + S] = base + np.arange(S)
                for j in range(S):
                    p = base + j
                    ints5[2, t + j] = s.block_table[p // bs] * bs + p % bs
                for off in range(0, S, S_C):
                    width = min(S_C, S - off)
                    grid_rows[tile] = i
                    ints5[3, t + off:t + off + width] = tile
                    ints5[4, t + off:t + off + width] = np.arange(width)
                    tile += 1
                rows3[i] = (t, S, len(s.tokens) + K)
                nblk = min(len(s.block_table), W)
                bt[i, :nblk] = s.block_table[:nblk]
                t += S
            assert tile <= C, f"verify grid overflow: {tile} > {C}"

            cursors = [_guided_fsm(seqs[gi]) for gi in grp]
            use_fsm = any(c is not None for c in cursors)
            self.compiled_signatures.add(
                ("verify_fsm" if use_fsm else "verify", T))
            self.padded_tokens_total += T - n * S
            operands = {"ints5": ints5, "rows3": rows3,
                        "grid_rows": grid_rows, "block_tables": bt}
            if use_fsm:
                # constrained rows verify under per-position FSM masks:
                # walk each cursor's compiled table along its draft
                # host-side (O(K) lookups, no device round trip) — a draft
                # token the mask forbids can never match the masked argmax,
                # so it is rejected at its position exactly as masked
                # single-step decode would reject it, and the bonus token
                # at the first mismatch is drawn from the correctly-
                # advanced state's mask.
                self._get_verify_masked_fn()
                W32 = self.structured.W32
                mw = np.empty((T, W32), np.uint32)
                mw[:] = np.uint32(0xFFFFFFFF)  # padding tokens: identity
                for i, c in enumerate(cursors):
                    if c is None:
                        continue
                    d = drafts[grp[i]]
                    fsm = c.seg.fsm
                    st = 0 if c.done else (c.state - c.seg.offset)
                    for j in range(S):
                        mw[i * S + j] = fsm.mask[st]
                        if j < len(d):
                            tok = d[j]
                            if tok in c._eos_set or not 0 <= tok < fsm.V:
                                st = 0
                            else:
                                st = int(fsm.next[st, tok])
                operands["mask_words"] = mw
                self._broadcast("verify_fsm", **operands)
                ids, lps, self.k_cache, self.v_cache = (
                    self._verify_masked_fn(
                        self.params,
                        *(self._put_batch(k, v)
                          for k, v in operands.items()),
                        self.k_cache, self.v_cache))
            else:
                self._broadcast("verify", **operands)
                ids, lps, self.k_cache, self.v_cache = self.verify_fn(
                    self.params,
                    *(self._put_batch(k, v) for k, v in operands.items()),
                    self.k_cache, self.v_cache)
            ids, lps = await asyncio.to_thread(
                lambda: (np.asarray(ids), np.asarray(lps)))

            for i, gi in enumerate(grp):
                s = seqs[gi]
                d = drafts[gi]
                q0 = i * S
                row_ids = ids[q0:q0 + S]
                row_lps = lps[q0:q0 + S]
                accepted = 0
                while (accepted < len(d)
                       and d[accepted] == int(row_ids[accepted])):
                    accepted += 1
                # emit accepted drafts + the corrected/bonus token as ONE
                # coalesced output; each commit marks the CURRENT tokens'
                # KV resident (the verify step computed it — accepted
                # drafts equal the real tokens) before the next append
                emitted = self._deliver_batch(s, row_ids[:accepted + 1],
                                              row_lps[:accepted + 1])
                # count what was actually DELIVERED — a seq finishing
                # mid-burst must not inflate acceptance telemetry
                self.spec_stats.num_drafts += 1
                self.spec_stats.num_draft_tokens += len(d)
                self.spec_stats.num_accepted_tokens += min(accepted, emitted)
                self.spec_stats.num_spec_tokens += emitted
                total_emitted += emitted
            self.param_reads += 1
        self._note_spec_result(total_emitted, len(seqs))
        return True

    # ------------------------------------------ spec auto-disable governor

    def _spec_active(self) -> bool:
        """False while the governor has speculative decode suspended (the
        rolling measured gain fell below 1 — drafting was a net slowdown).
        Re-probes automatically once ``spec_reprobe_steps`` steps pass."""
        return self.steps >= self._spec_resume_step

    def _spec_dispatch_cost(self) -> float:
        """Dispatch cost of one draft+verify round relative to a plain
        decode step. Re-baselined on MEASURED ragged dispatch walls: a
        verify row is just one more ragged chunk in the packed launch, so
        the static bucketed-dispatch constants below OVERESTIMATE its cost
        and made the governor suspend speculation too eagerly. When both
        EWMAs exist the measured ratio is used, floored at 1.01 (a round
        computes strictly more than a decode step) and capped at the
        static estimate (measurement only ever CHEAPENS spec — a noisy
        high sample must not suspend harder than the old model did)."""
        args = self.args
        if (args.speculative_method == "draft_layers"
                and args.speculative_draft_layers > 0):
            static = 1.0 + (args.speculative_tokens
                            * args.speculative_draft_layers
                            / max(1, self.cfg.num_layers))
        else:
            static = 1.05  # prompt lookup: free drafts, small overhead
        if (self._spec_round_ms is not None
                and self._decode_step_ms is not None
                and self._decode_step_ms > 0):
            return min(static,
                       max(1.01, self._spec_round_ms / self._decode_step_ms))
        return static

    def _note_spec_result(self, emitted: int, n_seqs: int) -> None:
        """Feed the governor one verify dispatch's outcome. When the mean
        tokens-per-dispatch over the window, discounted by the dispatch
        cost, stays under 1.0 (BENCH_r05: accept 0.019 → gain 0.729, a 27%
        slowdown with nothing turning it off), suspend speculation and
        re-probe after spec_reprobe_steps engine steps."""
        if self.args.spec_gain_window <= 0:
            return
        self._spec_window.append(emitted / max(1, n_seqs))
        if len(self._spec_window) < (self._spec_window.maxlen or 1):
            return
        gain = (sum(self._spec_window) / len(self._spec_window)
                / self._spec_dispatch_cost())
        self.spec_measured_gain = gain
        if gain < 1.0:
            self.spec_disabled_total += 1
            self._spec_resume_step = (self.steps
                                      + max(1, self.args.spec_reprobe_steps))
            self._spec_window.clear()
            logger.warning(
                "speculative decode suspended: measured gain %.3f < 1 over "
                "%d dispatches (accept rate %.3f); re-probing after %d "
                "steps", gain, self.args.spec_gain_window,
                self.spec_stats.num_accepted_tokens
                / max(1, self.spec_stats.num_draft_tokens),
                self.args.spec_reprobe_steps)

    async def _run_decode_fast(self, seqs: list[SeqState]) -> bool:
        # Burst/spec paths gate on the DECODE SUBSET only — not on a
        # globally-idle scheduler. The old `not waiting and all(running)`
        # gate meant any queued or mid-prefill request demoted every other
        # stream to one-token-per-dispatch; under continuous closed-loop
        # load that is the COMMON state, and each single step pays the full
        # dispatch+fetch round trip (~230 ms measured over the tunnel,
        # r4 step trace) — the fleet decoded at 31 tok/s while the kernel
        # does 4k+. A K-burst delays a pending prefill chunk by one burst
        # (~bounded TTFT cost) and buys K× fewer host round trips.
        # (plan.decode already contains only remaining==1 seqs — the
        # scheduler guarantees it, no per-step re-check needed)
        # Returns True when a fast path consumed the plan (with its own
        # flight record); False → the caller's packed ragged launch runs.
        t0 = time.perf_counter()
        gen0 = sum(s.generated for s in seqs)
        kind = None
        if (self.verify_fn is not None and seqs and self._spec_active()
                and all(s.sampling_tuple()[0] == 0.0 for s in seqs)
                and all(s.req.output_options.logprobs is None for s in seqs)
                and all(not s.req.sampling_options.logit_bias for s in seqs)
                and not any(_has_penalties(s) for s in seqs)
                # device-FSM constrained rows verify under per-position
                # masks (host oracle fallbacks still force single-step)
                and not any(_guided_host_only(s) for s in seqs)
                # a seq one token from its limit gains nothing from a draft
                and all((s.req.stop_conditions.max_tokens is None
                         or s.req.stop_conditions.max_tokens - s.generated >= 2)
                        for s in seqs)
                and await self._run_spec_decode(seqs)):
            kind = "spec"
        elif (self.multi_fn is not None and seqs
                # top-k capture and logit_bias need host-visible logits:
                # the burst keeps them on device, so those requests take
                # the single-step path
                and all(s.req.output_options.logprobs is None for s in seqs)
                and all(not s.req.sampling_options.logit_bias for s in seqs)
                and not any(_has_penalties(s) for s in seqs)
                # device-FSM rows mask + advance INSIDE the burst scan
                # (model.multi_decode fsm variant)
                and not any(_guided_host_only(s) for s in seqs)
                # NOTE a seq within K of max_tokens does NOT disqualify the
                # burst: its overshoot rows cost FLOPs on the batch dim, not
                # wall clock, while the old fallback cost EVERY stream K
                # single-step dispatch round trips whenever any one stream
                # was finishing — under continuous load, constantly
                and await self._run_multi_decode(seqs)):
            kind = "multi"
        if kind is None:
            return False
        wall = (time.perf_counter() - t0) * 1000
        self.step_trace.append((
            kind, len(seqs), sum(s.generated for s in seqs) - gen0, wall))
        self._flight_record(
            kind, wall, decode_rows=len(seqs),
            prefill_chunks=0, chunk_tokens=0,
            dispatch_ms=self._last_dispatch_ms,
            qos_mix=self._qos_mix_of(seqs),
            constrained=self._constrained_count(seqs),
            decode_seqs=seqs)
        return True

    # ------------------------------------------------- pipelined decode loop

    #: re-plan (admission, preemption, metrics) at least this often even
    #: when the pipeline could keep running — bounds how long a pipelined
    #: burst can defer scheduler housekeeping
    PIPELINE_REPLAN_STEPS = 64

    def _can_pipeline(self, seqs: list[SeqState]) -> bool:
        """True when the decode batch qualifies for the depth-2 pipelined
        loop: single-host, single-step decode, every running seq in the
        batch, and no request feature that forces a host round trip
        between sample and emit (logprob capture, logit edits, host-oracle
        guided fallbacks — device-FSM constrained rows ride the loop, the
        mask and state advance live inside the sampling dispatch)."""
        if not self.args.pipeline_decode or self._multihost or self._pp > 1:
            return False
        if self.multi_fn is not None or self.verify_fn is not None:
            return False
        # swapped seqs need plan() to run their swap-in admission promptly
        if (self.scheduler.waiting or self.scheduler.swapped
                or self.scheduler._aborted):
            return False
        # a running seq still mid-prefill needs plan() interleaving
        if len(seqs) != len(self.scheduler.running):
            return False
        for s in seqs:
            if (s.req.output_options.logprobs is not None
                    or s.req.sampling_options.logit_bias
                    or _has_penalties(s) or _guided_host_only(s)):
                return False
        return True

    def _dispatch_decode_step(self, seqs: list[SeqState], feed=None):
        """Dispatch ONE single-token decode step without any host sync.

        ``feed`` is the previous (uncommitted) step's handle: its sampled
        tokens are substituted into the token column ON DEVICE, so this
        dispatch never waits for the previous step's device→host copy.
        Positions/slots/tables only need token COUNTS, which the host knows
        before the token identities arrive. Returns a handle for
        _commit_decode_step, or None when block allocation fails (caller
        drains and falls back to plan(), which preempts).
        """
        import jax.numpy as jnp

        args = self.args
        bs = args.block_size
        off = 1 if feed is not None else 0  # uncommitted in-flight tokens
        for s in seqs:
            # this step writes KV at position len(s.tokens)-1+off → the
            # table must cover len+off tokens
            if not self.scheduler._ensure_blocks(s, len(s.tokens) + off):
                return None
        # ragged layout: decode row i is the single packed token at
        # flat index i — the feed substitution lands on ints5[0, :n].
        # Token arrays size to the T bucket, row/sampling/table arrays
        # to the (statically derived, R <= T) row count — the hot loop
        # must not memset T-bucket-sized host buffers it never reads.
        B = args.bucket_ragged_tokens(len(seqs))
        R = args.ragged_rows(B)
        W = args.max_blocks_per_seq

        A = R  # per-row host array size
        tokens = np.zeros((A, 1), np.int32)
        positions = np.zeros((A, 1), np.int32)
        slot_map = np.zeros((A, 1), np.int32)
        bt = np.full((A, W), NULL_BLOCK, np.int32)
        kv_lens = np.zeros((A,), np.int32)
        temp = np.zeros((A,), np.float32)
        top_k = np.zeros((A,), np.int32)
        top_p = np.ones((A,), np.float32)
        seeds, steps = [], []
        for i, s in enumerate(seqs):
            pos = len(s.tokens) - 1 + off
            if feed is None:
                tokens[i, 0] = s.tokens[-1]
            positions[i, 0] = pos
            slot_map[i, 0] = s.block_table[pos // bs] * bs + pos % bs
            n = min(len(s.block_table), W)
            bt[i, :n] = s.block_table[:n]
            kv_lens[i] = pos + 1
            t, k, p, seed = s.sampling_tuple()
            temp[i], top_k[i], top_p[i] = t, k, p
            seeds.append(seed if seed is not None
                         else hash(s.request_id) & 0x7FFFFFFF)
            # step_idx increments at commit; an uncommitted in-flight token
            # shifts this step's PRNG index by one (identical to what the
            # serial loop would use)
            steps.append(s.step_idx + off)
        seeds += [0] * (A - len(seqs))
        steps += [0] * (A - len(seqs))
        keys = self._sampling.make_keys(seeds, steps)

        self.param_reads += 1
        from dynamo_tpu.engine.model import ragged_grid_shape

        C, _ = ragged_grid_shape(B)
        ints5 = np.zeros((5, B), np.int32)
        ints5[0, :R] = tokens[:, 0]
        ints5[1, :R] = positions[:, 0]
        ints5[2, :R] = slot_map[:, 0]
        ints5[3] = C  # every token is decode: grid dump tile
        rows3 = np.zeros((R, 3), np.int32)
        rows3[:len(seqs), 0] = np.arange(len(seqs))
        rows3[:len(seqs), 1] = 1
        rows3[:len(seqs), 2] = kv_lens[:len(seqs)]
        ints5 = jnp.asarray(ints5)
        if feed is not None:
            ints5 = ints5.at[0, :len(seqs)].set(
                feed["toks"][:len(seqs)].astype(jnp.int32))
        new_sig = ("ragged_dec", B) not in self.compiled_signatures
        self.compiled_signatures.add(("ragged_dec", B))
        self.padded_tokens_total += B - len(seqs)
        t0 = time.perf_counter()
        logits, self.k_cache, self.v_cache = self.ragged_dec_fn(
            self.params, ints5, jnp.asarray(rows3),
            jnp.zeros((C,), jnp.int32), jnp.asarray(bt),
            self.k_cache, self.v_cache)
        if new_sig:
            self._note_compile("ragged_dec", (B,),
                               time.perf_counter() - t0)
        states = None
        if any(_guided_fsm(s) is not None for s in seqs):
            # constrained rows: per-row FSM state is one more device-fed
            # column — step N+1 dispatches with step N's advanced states
            # exactly like the token column, so the constraint costs no
            # host sync anywhere in the loop
            if feed is not None:
                states = feed["states"]
            else:
                st = np.zeros((A,), np.int32)
                for i, s in enumerate(seqs):
                    c = _guided_fsm(s)
                    if c is not None:
                        st[i] = c.state
                states = jnp.asarray(st)
        if states is not None:
            mask_t, next_t = self.structured.device_tables()
            toks, logps, states = self._sampling.sample_masked_jit(
                logits, temp, top_k, top_p, keys, states, mask_t, next_t)
        else:
            toks, logps = self._sampling.sample_jit(logits, temp, top_k,
                                                    top_p, keys)
        # device→host copy in a worker thread: the loop dispatches step N+1
        # and only then awaits this
        copy = asyncio.get_running_loop().create_task(asyncio.to_thread(
            lambda: (np.asarray(toks), np.asarray(logps))))
        return {"seqs": list(seqs), "toks": toks, "states": states,
                "copy": copy, "t0": t0}

    async def _commit_decode_step(self, handle) -> None:
        """Land one in-flight step: await its host copy, then commit + emit.
        Rows of sequences that finished at an earlier step are overshoot —
        their KV write targeted an unregistered block and is discarded."""
        toks, logps = await handle["copy"]
        n = 0
        constrained = 0
        for i, s in enumerate(handle["seqs"]):
            if s.finished is not None:
                continue
            self.scheduler.commit_computed(s, len(s.tokens))
            gs = _guided_fsm(s)
            if gs is not None:
                # host mirror of the on-device table advance (same table →
                # same state); lands before _deliver's check_finish reads
                # done/exhausted. O(1) numpy, never an oracle walk.
                gs.advance(int(toks[i]))
                constrained += 1
            self._deliver(s, int(toks[i]), float(logps[i]))
            n += 1
        self.pipelined_steps += 1
        wall = (time.perf_counter() - handle["t0"]) * 1000
        self.step_trace.append((
            "decode_pipe", len(handle["seqs"]), n, wall))
        self._flight_record(
            "decode_pipe", wall, decode_rows=n, prefill_chunks=0,
            chunk_tokens=0, starved=0, constrained=constrained,
            decode_seqs=handle["seqs"])

    async def _run_decode_pipelined(self, seqs: list[SeqState]) -> bool:
        """Depth-2 software pipeline over single-step decode.

        Serial loop per token: dispatch → device compute → host copy →
        commit/emit. Pipelined: step N+1 is dispatched (token column fed
        device-to-device from step N's sampler output) BEFORE step N's host
        copy is awaited, so the copy + Python bookkeeping + sink emission of
        step N overlap step N+1's device time. Greedy-invariant: positions,
        PRNG step indices and commits are exactly the serial loop's.

        Drains (commits every in-flight step) and returns whenever the
        steady state breaks: a sequence finished or was cancelled, new work
        arrived, allocation failed, or PIPELINE_REPLAN_STEPS elapsed.
        Returns True when at least one step ran.
        """
        prev = None
        done = 0
        try:
            while True:
                handle = self._dispatch_decode_step(seqs, feed=prev)
                if handle is None:
                    break  # allocation failure: plan() handles preemption
                done += 1
                # swap BEFORE the await: if the commit raises, ``prev`` is
                # the still-in-flight dispatch the except path must reap
                committed, prev = prev, handle
                if committed is not None:
                    await self._commit_decode_step(committed)
                if (done >= self.PIPELINE_REPLAN_STEPS or self._closed
                        or self.scheduler.waiting or self.scheduler.swapped
                        or self.scheduler._aborted
                        or any(s.finished is not None for s in seqs)
                        or any(getattr(s.ctx, "cancelled", False)
                               for s in seqs)):
                    break
        except BaseException:
            # surface the step failure, but never abandon an in-flight host
            # copy task (its late exception would be unretrieved)
            if prev is not None:
                prev["copy"].cancel()
                try:
                    await prev["copy"]
                except (Exception, asyncio.CancelledError):
                    pass
            raise
        if prev is not None:
            await self._commit_decode_step(prev)
        # _run adds 1 per _execute; top up so self.steps counts every
        # committed pipelined step exactly once
        self.steps += max(0, done - 1)
        return done > 0

    async def _run_multi_decode(self, seqs: list[SeqState]) -> bool:
        """Burst path: K decode steps in one dispatch. Returns False when a
        precondition fails (block preallocation) so the caller falls back to
        single-step."""
        import jax.numpy as jnp

        args = self.args
        K = args.multi_step_decode
        # the burst writes positions len-1 .. len+K-2 → len+K-1 slots
        if not self._prealloc_blocks(seqs, K - 1):
            return False

        # ragged-family signature: the row bucket derives from the token
        # bucket (one token per row) and the table width is static, so the
        # burst adds no (B × W) lattice of its own
        B = args.ragged_rows(args.bucket_ragged_tokens(len(seqs)))
        W = args.max_blocks_per_seq

        last_tokens = np.zeros((B,), np.int32)
        positions = np.zeros((B,), np.int32)
        bt = np.full((B, W), NULL_BLOCK, np.int32)
        kv_lens = np.zeros((B,), np.int32)
        temp = np.zeros((B,), np.float32)
        top_k = np.zeros((B,), np.int32)
        top_p = np.ones((B,), np.float32)
        seeds = np.zeros((B,), np.uint32)
        step0 = np.zeros((B,), np.uint32)
        for i, s in enumerate(seqs):
            last_tokens[i] = s.tokens[-1]
            positions[i] = len(s.tokens) - 1
            n = min(len(s.block_table), W)
            bt[i, :n] = s.block_table[:n]
            kv_lens[i] = len(s.tokens)
            t, k, p, seed = s.sampling_tuple()
            temp[i], top_k[i], top_p[i] = t, k, p
            seeds[i] = (seed if seed is not None
                        else hash(s.request_id) & 0x7FFFFFFF) & 0xFFFFFFFF
            step0[i] = s.step_idx & 0xFFFFFFFF

        # packed operands: 4 transfers per K-token burst instead of 9
        # (each small put is ~12 ms over a tunneled chip — r4 step trace)
        ints = np.stack([last_tokens, positions, kv_lens, top_k], axis=1)
        floats = np.stack([temp, top_p], axis=1)
        rand = np.stack([seeds, step0], axis=1)
        cursors = [_guided_fsm(s) for s in seqs]
        use_fsm = any(c is not None for c in cursors)
        kind = "multi_fsm" if use_fsm else "multi"
        new_sig = (kind, B) not in self.compiled_signatures
        self.compiled_signatures.add((kind, B))
        self.padded_tokens_total += (B - len(seqs)) * K
        self._broadcast("multi", ints=ints, floats=floats, rand=rand,
                        block_tables=bt)
        self.param_reads += K
        t0d = time.perf_counter()
        if use_fsm:
            # constrained rows: per-row FSM state rides the burst scan —
            # masked sampling + table advance on device each of the K
            # steps (free rows carry the arena's identity state 0)
            import jax.numpy as _jnp
            if self._multi_fsm_fn is None:
                from dynamo_tpu.engine import model as M
                self._multi_fsm_fn = M.make_multi_decode_fn(
                    self.cfg, args.block_size, K, self.mesh,
                    use_pallas=args.use_pallas_attention,
                    replicate_outputs=self._multihost,
                    kv_quant=self._kv_quant, fsm=True)
            states = np.zeros((B,), np.int32)
            for i, c in enumerate(cursors):
                if c is not None:
                    states[i] = c.state
            mask_t, next_t = self.structured.device_tables()
            toks, logps, self.k_cache, self.v_cache = self._multi_fsm_fn(
                self.params, self._put_batch("ints", ints),
                self._put_batch("floats", floats),
                self._put_batch("rand", rand),
                self._put_batch("block_tables", bt),
                _jnp.asarray(states), mask_t, next_t,
                self.k_cache, self.v_cache)
        else:
            toks, logps, self.k_cache, self.v_cache = self.multi_fn(
                self.params, self._put_batch("ints", ints),
                self._put_batch("floats", floats),
                self._put_batch("rand", rand),
                self._put_batch("block_tables", bt),
                self.k_cache, self.v_cache)
        self._last_dispatch_ms = (time.perf_counter() - t0d) * 1000
        if new_sig:
            self._note_compile(kind, (B,), time.perf_counter() - t0d)
        toks, logps = await asyncio.to_thread(
            lambda: (np.asarray(toks), np.asarray(logps)))

        for i, s in enumerate(seqs):
            # one coalesced output per seq per burst (overshoot discarded)
            self._deliver_batch(s, toks[:, i], logps[:, i])
        return True

    # ------------------------------------------------------------ sampling


    def _put_batch(self, name: str, arr):
        """Host batch array → device array; under a multi-host mesh the
        array becomes a GLOBAL array (batch axis on "dp", replicated when
        dp=1) so every rank's jitted call sees identical operands."""
        import jax.numpy as jnp

        if not self._multihost:
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding, PartitionSpec as P

        from dynamo_tpu.parallel.multihost import global_put

        a = np.asarray(arr)
        spec = P(*(["dp"] + [None] * (a.ndim - 1)))
        return global_put(a, NamedSharding(self.mesh, spec))

    def _broadcast(self, kind: str, **arrays) -> None:
        if self.broadcast_cb is not None:
            self.broadcast_cb(kind, arrays)

    async def _sample(self, seqs: list[SeqState], logits, rows=None):
        """Sample one token per seq from padded logits [B>=len(seqs), V].

        ``rows`` (multi-host batched prefill): bucket-padded row indices to
        gather from ``logits`` host-side, inside the worker thread — the
        sync must stay off the event loop, and the gather must be local
        (never a device op on the replicated global array).

        Returns (tokens, logps, tops) — ``tops[i]`` is the row's top-k
        [token_id, logprob] alternatives when seq i requested logprobs
        (ref surface: perf/logprobs.rs TokenLogProbs), else absent.
        """
        B = len(rows) if rows is not None else logits.shape[0]
        temp = np.zeros((B,), np.float32)
        top_k = np.zeros((B,), np.int32)
        top_p = np.ones((B,), np.float32)
        seeds, steps = [], []
        want_tops: dict[int, int] = {}
        for i, s in enumerate(seqs):
            t, k, p, seed = s.sampling_tuple()
            temp[i], top_k[i], top_p[i] = t, k, p
            seeds.append(seed if seed is not None else hash(s.request_id) & 0x7FFFFFFF)
            steps.append(s.step_idx)
            n = s.req.output_options.logprobs
            if n is not None:  # 0 still captures the selected token's entry
                want_tops[i] = max(1, min(int(n), 20))
        seeds += [0] * (B - len(seqs))
        steps += [0] * (B - len(seqs))
        keys = self._sampling.make_keys(seeds, steps)

        V = logits.shape[-1]

        def build_triples():
            # sparse logit edits — at most a few hundred entries per row,
            # never a dense [B, V] materialization. Built in the worker
            # thread: the per-seq history scans (Counter over generated
            # tokens, set over the full sequence) are O(context) and must
            # not run on the event loop. seqs are not mutated while a step
            # is in flight (the engine loop delivers only after _sample).
            b_rows, b_cols, b_vals = [], [], []  # additive: bias + penalties
            # repetition penalty is multiplicative read-modify-write (HF
            # semantics: logit>0 -> /p else *p, over prompt+generated), so
            # it gets its own triples, applied BEFORE the additive terms
            r_rows, r_cols, r_pens = [], [], []
            for i, s in enumerate(seqs):
                so = s.req.sampling_options
                for tid, v in (so.logit_bias or {}).items():
                    t = int(tid)
                    if 0 <= t < V:
                        b_rows.append(i)
                        b_cols.append(t)
                        b_vals.append(v)
                pres = so.presence_penalty or 0.0
                freq = so.frequency_penalty or 0.0
                rep = so.repetition_penalty
                rep_on = rep is not None and rep > 0 and rep != 1.0
                if pres or freq or rep_on:
                    # fold new history incrementally (ngram_pos pattern):
                    # O(new tokens) per step, not O(context)
                    for j in range(s.pen_indexed, len(s.tokens)):
                        t = s.tokens[j]
                        s.seen_tokens.add(t)
                        if j >= s.prompt_len:
                            s.gen_counts[t] = s.gen_counts.get(t, 0) + 1
                    s.pen_indexed = len(s.tokens)
                if pres or freq:
                    # OpenAI semantics: counted over the GENERATED text
                    # only — rides the same sparse scatter-add as logit_bias
                    for tid, cnt in s.gen_counts.items():
                        if 0 <= tid < V:
                            b_rows.append(i)
                            b_cols.append(int(tid))
                            b_vals.append(-(pres + freq * cnt))
                if rep_on:
                    for tid in s.seen_tokens:
                        if 0 <= tid < V:
                            r_rows.append(i)
                            r_cols.append(int(tid))
                            r_pens.append(float(rep))
            # guided decoding: rows whose logits are masked to the
            # constraint's allowed set (allowed() walks the vocab once per
            # NEW dfa state — here in the worker thread, cached after)
            def g_allowed(s):
                ids = s.guided_state.allowed_token_ids(V)
                if (s.req.stop_conditions.min_tokens or 0) > s.generated:
                    # min_tokens: suppress EOS from the allowed set (the
                    # unguided path gates EOS the same way) — unless EOS is
                    # all the constraint has left, where stopping beats an
                    # all-masked step
                    non_eos = [t for t in ids
                               if t not in s.guided_state.eos_ids]
                    if non_eos:
                        return non_eos
                return ids
            # host-oracle guided rows mask via sparse host logit edits;
            # device-FSM rows (FsmCursor) mask inside the fused sampling
            # dispatch below. Logprob capture is the exception: top-k must
            # read the SAME masked logits the sampler saw, so those rows
            # fall back to the host edit too.
            g_rows = [(i, g_allowed(s)) for i, s in enumerate(seqs)
                      if _guided_host_only(s)
                      or (want_tops and _guided_fsm(s) is not None)]
            fsm_rows = ([] if want_tops else
                        [(i, c) for i, s in enumerate(seqs)
                         if (c := _guided_fsm(s)) is not None])
            return (b_rows, b_cols, b_vals, r_rows, r_cols, r_pens, g_rows,
                    fsm_rows)

        def run_sampling():
            # runs in a worker thread: the host sync below must NEVER block
            # the event loop — under multi-host it waits on a collective the
            # FOLLOWER ranks can only join after the loop's broadcaster task
            # flushed the step (blocking the loop here deadlocked the fleet)
            (b_rows, b_cols, b_vals, r_rows, r_cols, r_pens,
             g_rows, fsm_rows) = build_triples()
            lg = logits
            if self._multihost or isinstance(lg, np.ndarray):
                # logits are fully replicated (replicate_logits): round-trip
                # through host so sampling is a LOCAL computation — a global
                # op here would have to be mirrored by every follower rank
                # (this includes the penalty/bias edits below: numpy, never
                # a device op on the global array). Device-FSM rows mask
                # host-side here too — bit-unpack of the table row, same
                # allowed set as the fused gather.
                lg = np.asarray(lg)
                if rows is not None:
                    lg = lg[np.asarray(rows)]  # fancy index: fresh, writable
                elif r_rows or b_rows or g_rows or fsm_rows:
                    lg = lg.copy()
                if r_rows:
                    v = lg[r_rows, r_cols]
                    rp = np.asarray(r_pens, lg.dtype)
                    lg[r_rows, r_cols] = np.where(v > 0, v / rp, v * rp)
                if b_rows:
                    np.add.at(lg, (b_rows, b_cols), b_vals)
                for i, allowed in (g_rows
                                   + [(i, c.allowed_token_ids(V))
                                      for i, c in fsm_rows]):
                    masked = np.full((lg.shape[-1],), -1e30, lg.dtype)
                    if allowed:
                        ai = np.asarray(allowed)
                        masked[ai] = lg[i, ai]
                    lg[i] = masked
                fsm_rows = []
            elif r_rows or b_rows or g_rows:
                # single-host: tiny device gather/scatter
                import jax.numpy as jnp

                if r_rows:
                    rr = jnp.asarray(r_rows)
                    rc = jnp.asarray(r_cols)
                    rp = jnp.asarray(r_pens, lg.dtype)
                    v = lg[rr, rc]
                    lg = lg.at[rr, rc].set(jnp.where(v > 0, v / rp, v * rp))
                if b_rows:
                    lg = lg.at[jnp.asarray(b_rows), jnp.asarray(b_cols)].add(
                        jnp.asarray(b_vals, lg.dtype))
                for i, allowed in g_rows:
                    masked = jnp.full((lg.shape[-1],), -1e30, lg.dtype)
                    if allowed:
                        ai = jnp.asarray(allowed)
                        masked = masked.at[ai].set(lg[i, ai])
                    lg = lg.at[i].set(masked)
            if fsm_rows:
                # fused constrained sampling: the FSM mask is a packed-
                # bitmask gather INSIDE the jitted dispatch — no host
                # materialization, no per-row Python (docs/structured.md)
                import jax.numpy as jnp

                states = np.zeros((B,), np.int32)
                for i, c in fsm_rows:
                    states[i] = c.state
                mask_t, next_t = self.structured.device_tables()
                toks, logps, _ = self._sampling.sample_masked_jit(
                    lg, temp, top_k, top_p, keys, jnp.asarray(states),
                    mask_t, next_t)
            else:
                toks, logps = self._sampling.sample_jit(lg, temp, top_k,
                                                        top_p, keys)
            top_res = None
            if want_tops:
                # device-side top-k: only O(B·k) crosses to host, and the
                # selected logprob comes from the same log_softmax as its
                # alternatives (an ulp disagreement would read as a fake
                # near-tie). Always the k=20 kernel — one XLA compile ever,
                # sliced per row below
                top_res = self._sampling.make_topk_logprobs_fn(20)(lg, toks)
            t, l = np.asarray(toks), np.asarray(logps)
            for gi, gs in enumerate(seqs):
                if gs.guided_state is not None:
                    # advance here, in the worker thread: a newly-visited
                    # DFA state triggers an O(vocab) walk that must stay
                    # off the event loop (_deliver does not advance)
                    gs.guided_state.advance(int(t[gi]))
            tops: dict[int, list[list]] = {}
            if top_res is not None:
                ids, vals, sel = (np.asarray(x) for x in top_res)
                l = l.copy()
                for i, n in want_tops.items():
                    tops[i] = [[int(j), float(v)]
                               for j, v in zip(ids[i, :n], vals[i, :n])]
                    l[i] = sel[i]
            return t, l, tops

        return await asyncio.to_thread(run_sampling)

    def _deliver_batch(self, seq: SeqState, tokens, logps) -> int:
        """Coalesced per-step emission: commit/append each token of a fused
        burst, but put ONE LLMEngineOutput on the sink for the whole step —
        one queue item → one detokenizer iteration → one SSE write instead
        of K of each. Tokens past a finish are discarded (overshoot rows).
        Returns the number of tokens actually delivered."""
        ids: list[int] = []
        lps: list[float] = []
        reason = None
        gs = seq.guided_state
        for t, lp in zip(tokens, logps):
            self.scheduler.commit_computed(seq, len(seq.tokens))
            self.scheduler.append_token(seq, int(t))
            ids.append(int(t))
            lps.append(float(lp))
            if gs is not None:
                # device-FSM cursor: one numpy table lookup — must land
                # before check_finish reads done/exhausted (only device
                # rows reach the fused paths, so this is never an
                # O(vocab) oracle walk on the event loop)
                gs.advance(int(t))
            reason = self.scheduler.check_finish(seq, int(t))
            if reason is not None:
                break
        if not ids:
            return 0
        if reason is not None:
            self.scheduler.finish(seq, reason)
        seq.sink.put_nowait(LLMEngineOutput(token_ids=ids, log_probs=lps,
                                            finish_reason=reason))
        if reason is not None:
            seq.sink.put_nowait(None)
        return len(ids)

    def _deliver(self, seq: SeqState, token: int, logp: float,
                 top: Optional[list] = None) -> None:
        self.scheduler.append_token(seq, token)
        reason = self.scheduler.check_finish(seq, token)
        out = LLMEngineOutput(token_ids=[token], log_probs=[logp],
                              top_logprobs=[top] if top is not None else None,
                              finish_reason=reason)
        if reason is not None:
            self.scheduler.finish(seq, reason)
        seq.sink.put_nowait(out)
        if reason is not None:
            seq.sink.put_nowait(None)

    # ------------------------------------------------------------- events

    def _on_stored(self, parent_hash, blocks: list[StoredBlock],
                   block_ids: Optional[list[int]] = None) -> None:
        if self.event_cb:
            self.event_cb(KvCacheEvent.stored(next(self._event_id), parent_hash, blocks))
        if self.kvbm is not None and block_ids:
            hashes = [b.block_hash for b in blocks]
            fresh = [(h, bid) for h, bid in zip(hashes, block_ids)
                     if h not in self.kvbm]
            if fresh:
                self._spawn_offload([h for h, _ in fresh],
                                    [bid for _, bid in fresh])

    # ----------------------------------------------------- KVBM offload/onboard

    def _spawn_remote_fetch(self, hashes: list) -> None:
        """G4→G2: pull prefix blocks held by PEER workers into the local
        host tier (distributed KVBM — ref: block_manager/distributed/
        leader.rs cross-worker onboarding). Same discipline as the disk
        promotion: the admission path never blocks on the network; the next
        admission of the prefix onboards from host."""
        if getattr(self, "_remote_fetching", None) is None:
            self._remote_fetching = set()
        todo = [h for h in hashes if h not in self._remote_fetching]
        if not todo:
            return
        self._remote_fetching.update(todo)

        async def run():
            try:
                await self.kvbm_remote.fetch_into_host(todo)
            except Exception:
                logger.exception("KVBM remote fetch failed")
            finally:
                self._remote_fetching.difference_update(todo)

        task = asyncio.get_running_loop().create_task(run())
        self._offload_tasks.add(task)
        task.add_done_callback(self._offload_tasks.discard)

    def _spawn_promote(self, hashes: list) -> None:
        """G3→G2 in a worker thread (np.load off the event loop)."""
        if getattr(self, "_promoting", None) is None:
            self._promoting = set()
        todo = [h for h in hashes if h not in self._promoting]
        if not todo:
            return
        self._promoting.update(todo)

        async def run():
            try:
                # reverse order: if the host tier can't hold the whole run,
                # it must end up holding the EARLIEST blocks — a prefix is
                # only usable from its first block
                for h in reversed(todo):
                    await asyncio.to_thread(self.kvbm.get, h)  # get() promotes
            except Exception:
                logger.exception("KVBM disk promotion failed")
            finally:
                self._promoting.difference_update(todo)

        task = asyncio.get_running_loop().create_task(run())
        self._offload_tasks.add(task)
        task.add_done_callback(self._offload_tasks.discard)

    def _spawn_offload(self, seq_hashes: list, block_ids: list[int]) -> None:
        """G1→G2: pin the blocks, gather their pages once, park on host."""
        self.pool.acquire(block_ids)
        task = asyncio.get_running_loop().create_task(
            self._offload(seq_hashes, block_ids))
        self._offload_tasks.add(task)
        task.add_done_callback(self._offload_tasks.discard)

    async def _offload(self, seq_hashes: list, block_ids: list[int]) -> None:
        from dynamo_tpu.ops.block_copy import gather_blocks

        try:
            bs = self.args.block_size
            kb = gather_blocks(self.k_cache, block_ids, block_size=bs)
            vb = gather_blocks(self.v_cache, block_ids, block_size=bs)

            def work():  # host transfer + tier writes off the event loop
                kbh, vbh = np.asarray(kb), np.asarray(vb)
                for i, h in enumerate(seq_hashes):
                    # copies, not views: a view would pin the whole
                    # pow2-padded gather buffer past the tier byte budget
                    self.kvbm.put(h, np.ascontiguousarray(kbh[:, i]),
                                  np.ascontiguousarray(vbh[:, i]))

            await asyncio.to_thread(work)
        except Exception:
            logger.exception("KVBM offload failed")
        finally:
            self.pool.release(block_ids)

    def _note_hot_prefix(self, probe, n: int) -> None:
        """Scheduler prefix-HIT hook (G4 flow-up, docs/performance.md):
        count repeat hits per block; leading runs whose blocks cross
        DYN_G4_PUBLISH_HITS are pushed up to the G4 object store so the
        whole fleet — including cold-started workers — can warm from
        them. Hits arrive leading-run-shaped, so a block's ancestors
        always cross the threshold no later than it does and the G4
        radix chain stays root-anchored."""
        if (self._g4_publish_hits <= 0 or self.kvbm is None
                or self.kvbm.remote is None):
            return
        hashes = probe.sequence_hashes()[:n]
        if len(self._prefix_hits) > (1 << 16):
            # bounded popularity state: drop the oldest half (dict order =
            # insertion order; hot prefixes re-earn their counts quickly)
            for h in list(itertools.islice(self._prefix_hits, 1 << 15)):
                del self._prefix_hits[h]
        todo = []
        for h in hashes:
            c = self._prefix_hits.get(h, 0) + 1
            self._prefix_hits[h] = c
            if c >= self._g4_publish_hits and h not in self._g4_publishing:
                todo.append(h)
        if not todo:
            return
        self._g4_publishing.update(todo)

        async def run():
            try:
                # tier reads + object-store writes off the event loop, in
                # prefix order (parents first — the announcer's chain
                # rule). The thread only READS engine state; _prefix_hits
                # is mutated exclusively on the loop (below), so the trim
                # above can never race a cross-thread pop.
                def work():
                    already = self.kvbm.remote_resident(todo)
                    missed, queued = [], 0
                    for h in todo:
                        if h in already:
                            continue  # LRU-touched; no byte read needed
                        e = self.kvbm.get_local(h)
                        if e is None:
                            missed.append(h)
                            continue
                        if self.kvbm.publish_remote(h, e[0], e[1],
                                                    drain=False):
                            # drain every 16 queued writes: one drain
                            # cycle per batch, bounded payload residency
                            # in the op queue
                            queued += 1
                            if queued % 16 == 0:
                                self.kvbm.drain_remote()
                    if queued % 16:
                        self.kvbm.drain_remote()
                    return missed

                for h in await asyncio.to_thread(work):
                    # device-only so far (the G1→G2 offload is still in
                    # flight): forget the threshold crossing so the NEXT
                    # hit retries once the bytes reach a tier
                    self._prefix_hits.pop(h, None)
            except Exception:
                logger.exception("G4 prefix flow-up failed")
            finally:
                self._g4_publishing.difference_update(todo)

        task = asyncio.get_running_loop().create_task(run())
        self._offload_tasks.add(task)
        task.add_done_callback(self._offload_tasks.discard)

    async def onboard_remote(self, probe, start: int, end: int) -> int:
        """G4 → host → device warmup at admission (routine onboarding's
        cold-start path, docs/performance.md): fetch the leading run of
        ``probe``'s missing blocks [start, end) out of the fleet-global
        object store into the host tier (worker thread — blocking plane
        I/O), then scatter/register them like any KVBM onboard. The
        attached blocks park in the LRU (refcount 0) for the subsequent
        generate()'s prefix match, so a failure leaks nothing. Returns
        blocks attached."""
        if self.kvbm is None or self.kvbm.remote is None or end <= start:
            return 0
        hashes = probe.sequence_hashes()[start:end]
        landed = await asyncio.to_thread(self.kvbm.fetch_remote, hashes)
        if not landed:
            return 0
        ids = self._onboard(probe, start, start + landed)
        if not ids:
            return 0
        self.pool.release(ids)
        return len(ids)

    def _onboard(self, probe, start: int, end: int) -> list[int]:
        """G2→G1 at admission: missing prefix blocks found in the HOST tier
        are scattered into fresh device blocks (synchronous — it replaces a
        much more expensive recompute). Disk-resident blocks are NOT read
        here — np.load inside plan() would stall every in-flight decode —
        instead a background promotion pulls them G3→G2 so the next
        admission of the prefix hits host."""
        hashes = probe.sequence_hashes()[start:end]
        ks, vs = [], []
        for i, h in enumerate(hashes):
            e = self.kvbm.get_host(h)
            if e is None:
                if self.kvbm.in_lower_tier(h):  # G3 disk or G4 remote
                    self._spawn_promote(hashes[i:])
                elif self.kvbm_remote is not None:
                    self._spawn_remote_fetch(hashes[i:])
                break
            ks.append(e[0])
            vs.append(e[1])
        if not ks:
            return []
        ids = self._scatter_register(probe, start, ks, vs)
        if ids is None:
            return []
        self.kvbm.onboarded_blocks += len(ks)
        return ids

    # ------------------------------------------------------ preempt-to-swap
    #
    # The scheduler's swapper backend: under KV pressure a victim's device
    # pages move to host DRAM (swap_out) and return before its next planned
    # step (swap_in) instead of being recomputed from scratch. Bundles ride
    # the SAME formats the G2 tier and the disagg wire use — value arrays
    # for plain caches, packed (q, s) uint8 for int8 caches — so the
    # round-trip is bit-exact by construction for both.

    def _swap_block_bytes(self) -> int:
        """Host bytes one swapped block costs (k + v, actual n — the pow2
        gather padding is sliced off before the bundle is retained)."""
        cached = getattr(self, "_swap_blk_bytes", None)
        if cached is not None:
            return cached
        from dynamo_tpu.engine.cache import (
            cache_shape, is_quant_cache, packed_block_width,
        )

        bs = self.args.block_size
        total = 0
        for cache in (self.k_cache, self.v_cache):
            L, _slots, KV, hd = cache_shape(cache)
            if is_quant_cache(cache):
                total += L * packed_block_width(bs, KV, hd)  # uint8
            else:
                total += L * bs * KV * hd * cache.dtype.itemsize
        self._swap_blk_bytes = total
        return total

    def swap_out(self, seq: SeqState) -> bool:
        """Stage ``seq``'s computed KV on host; True = the scheduler may
        release its device blocks and park it in the swapped queue.

        The gathers are dispatched HERE, synchronously, against the current
        immutable cache arrays — device program order guarantees they read
        the pages before any later step reuses the slots, so the blocks are
        free for reallocation the moment this returns (same capacity
        timing as recompute preemption). Only the device→host copy runs
        async, overlapped with the next steps exactly like _spawn_offload.
        """
        from dynamo_tpu.ops.block_copy import gather_blocks

        bs = self.args.block_size
        n = (seq.num_computed + bs - 1) // bs  # blocks holding computed KV
        if n <= 0 or n > len(seq.block_table):
            return False
        nbytes = n * self._swap_block_bytes()
        if not self._swap.reserve(nbytes):
            return False  # host budget exhausted → recompute fallback
        entry = _SwapEntry(n, nbytes)
        try:
            ids = seq.block_table[:n]
            kb = gather_blocks(self.k_cache, ids, block_size=bs)
            vb = gather_blocks(self.v_cache, ids, block_size=bs)
        except Exception:
            logger.exception("swap-out gather dispatch failed for %s",
                             seq.request_id)
            self._swap.release(nbytes)
            return False
        seq.swap = entry
        self.swap_out_blocks += n
        self.pool.note_swapped_out(n)

        async def copy():
            try:
                def work():
                    # contiguous copies, not views: a view would pin the
                    # whole pow2-padded gather buffer past the budget
                    entry.k = np.ascontiguousarray(np.asarray(kb)[:, :n])
                    entry.v = np.ascontiguousarray(np.asarray(vb)[:, :n])

                await asyncio.to_thread(work)
                entry.ready = True
            except Exception:
                logger.exception("swap-out host copy failed for %s",
                                 seq.request_id)
                entry.failed = True
                self._swap_free(entry)
            finally:
                if entry.dropped:
                    self._swap_free(entry)
                self._wake.set()  # a ready bundle can unblock plan()

        task = asyncio.get_running_loop().create_task(copy())
        self._offload_tasks.add(task)
        task.add_done_callback(self._offload_tasks.discard)
        return True

    def swap_status(self, seq: SeqState) -> str:
        entry = seq.swap
        if entry is None or entry.failed or entry.freed:
            return "failed"
        return "ready" if entry.ready else "pending"

    def swap_in(self, seq: SeqState) -> bool:
        """Scatter the host bundle back into the freshly allocated block
        table. No host sync needed: the scatter produces the new cache
        arrays the next jitted step consumes, so device data dependencies
        order it before any read of those pages."""
        from dynamo_tpu.ops.block_copy import scatter_blocks

        entry: _SwapEntry = seq.swap
        if (entry is None or not entry.ready or entry.failed or entry.freed
                or len(seq.block_table) < entry.n):
            return False
        bs = self.args.block_size
        ids = seq.block_table[:entry.n]
        try:
            self.k_cache = scatter_blocks(self.k_cache, ids, entry.k,
                                          block_size=bs)
            self.v_cache = scatter_blocks(self.v_cache, ids, entry.v,
                                          block_size=bs)
        except Exception:
            logger.exception("swap-in scatter failed for %s", seq.request_id)
            entry.failed = True
            self.pool.note_swapped_in(entry.n)
            self._swap_free(entry)
            seq.swap = None
            return False
        self.swap_in_blocks += entry.n
        self.pool.note_swapped_in(entry.n)
        self._swap_free(entry)
        seq.swap = None
        # re-register the returning full blocks so the prefix cache serves
        # them again; fresh registrations (hash no longer resident via the
        # LRU) are re-announced so the router's radix view heals
        stored: list[StoredBlock] = []
        stored_ids: list[int] = []
        parent = None
        for i in range(min(seq.num_registered_blocks, entry.n)):
            blk = seq.hashes.blocks[i]
            if self.pool.register(seq.block_table[i], blk.sequence_hash,
                                  blk.block_hash, blk.parent_sequence_hash):
                if not stored:
                    parent = blk.parent_sequence_hash
                stored.append(StoredBlock(block_hash=blk.sequence_hash,
                                          tokens_hash=blk.block_hash))
                stored_ids.append(seq.block_table[i])
        if stored:
            self._on_stored(parent, stored, stored_ids)
        return True

    def swap_drop(self, seq: SeqState) -> None:
        """Cancel-safe teardown: free the bundle + budget (or mark the
        in-flight copy to free itself on completion)."""
        entry: _SwapEntry = seq.swap
        if entry is None:
            return
        seq.swap = None
        entry.dropped = True
        self.pool.note_swapped_in(entry.n)
        if entry.ready or entry.failed:
            self._swap_free(entry)

    def _swap_free(self, entry: "_SwapEntry") -> None:
        if entry.freed:
            return
        entry.freed = True
        entry.k = entry.v = None
        self._swap.release(entry.nbytes)

    def swap_stats(self) -> dict:
        """Telemetry for /metrics (engine/main.py gauge/counter callbacks)."""
        sched = self.scheduler
        return {
            "swap_out_blocks": self.swap_out_blocks,
            "swap_in_blocks": self.swap_in_blocks,
            "preempt_swap": sched.preempt_swap_total,
            "preempt_recompute": sched.preempt_recompute_total,
            "swap_in_seqs": sched.swap_in_total,
            "recomputed_tokens": sched.recomputed_tokens_total,
            "swapped_seqs": len(sched.swapped),
            "swapped_blocks": self.pool.swapped_blocks,
            "swap_host_bytes": self._swap.used if self._swap else 0,
            "swap_host_budget": self._swap.budget if self._swap else 0,
            "swap_in_blocked": sched.swap_in_blocked_total,
        }

    def qos_stats(self) -> dict:
        """Per-(tenant, class) QoS telemetry: served tokens, queue wait,
        preemptions (→ dynamo_tenant_* metrics, engine/main.py)."""
        return self.scheduler.qos.snapshot()

    def _on_removed(self, seq_hashes) -> None:
        if self.event_cb is None:
            return
        if seq_hashes is None:
            self.event_cb(KvCacheEvent.clear(next(self._event_id)))
            return
        # fleet-wide KV hierarchy (docs/robustness.md): a device eviction
        # whose block survives in this worker's G2/G3 tiers is NOT gone —
        # admission onboards it back and restore pulls serve it
        # (export_blocks reads exactly host+disk) — so it must stay in
        # the global radix index. The removed event fires only when the
        # last LOCALLY-SERVABLE copy dies (here, or via the KVBM bridge
        # below when the tiers finally evict it). A G4-only block does
        # NOT suppress the removal: the remote index is not servable by
        # kv_pull, and advertising it would burn peers' pull attempts.
        if self.kvbm is not None:
            seq_hashes = self.kvbm.filter_not_local(seq_hashes)
        if seq_hashes:
            self.event_cb(KvCacheEvent.removed(next(self._event_id), list(seq_hashes)))

    def _on_kvbm_change(self, stored, removed) -> None:
        """KvbmManager.on_change bridge: when a hash leaves the LAST KVBM
        tier and is not device-resident either, announce the removal to
        the router — without this the radix would keep advertising KV
        this worker can no longer serve (stale restore sources / inflated
        overlap). Stored hashes need no event: blocks enter the tiers
        from the device (offload), which already announced them.

        Known G4 edge: on_change reports removal only when a hash leaves
        EVERY tier, so a block cascading G3→G4 keeps its radix entry
        until the G4 copy dies even though kv_pull cannot serve it (the
        distributed-KVBM fetch endpoint can, which is why the manager's
        contract is all-tiers). Cost: a peer's restore wastes one pull
        attempt and fails over; bounded, and only with G4 armed.

        Fired under the manager lock, possibly from an offload worker
        thread — publishing hops onto the engine's loop when needed
        (the event task machinery is loop-affine)."""
        if self.event_cb is None or not removed:
            return

        def emit():
            gone = [h for h in removed if self.pool.lookup(h) is None]
            if gone and self.event_cb is not None:
                self.event_cb(KvCacheEvent.removed(next(self._event_id),
                                                   gone))

        try:
            asyncio.get_running_loop()
        except RuntimeError:
            loop = self._loop_ref
            if loop is not None and not loop.is_closed():
                loop.call_soon_threadsafe(emit)
            return
        emit()

    def _metrics(self) -> ForwardPassMetrics:
        from dynamo_tpu.engine.model import MOE_DROPS

        sched = self.scheduler
        active = self.pool.num_active_blocks
        return ForwardPassMetrics(
            worker_stats=WorkerStats(
                request_active_slots=len(sched.running),
                request_total_slots=self.args.max_num_seqs,
                # swapped seqs count as waiting load: they hold no device
                # blocks but WILL reclaim capacity before new admissions
                num_requests_waiting=sched.num_waiting() + len(sched.swapped),
                data_parallel_rank=self.dp_rank,
                moe_dropped_tokens=MOE_DROPS["total"],
                # cold = warmup was requested but skipped (multi-host) and
                # no real step has compiled yet; workers that never asked
                # for warmup report None (legacy semantics: counted warm)
                warmed_up=(None if not self.warmup_requested
                           else not self.warmup_skipped or self.steps > 0),
            ),
            kv_stats=KvStats(
                kv_active_blocks=active,
                kv_total_blocks=self.num_blocks - 1,
                gpu_cache_usage_perc=self.pool.usage(),
                gpu_prefix_cache_hit_rate=(
                    sched.prefix_hit_tokens / sched.prefix_query_tokens
                    if sched.prefix_query_tokens else 0.0),
            ),
            spec_decode_stats=(self.spec_stats
                               if self.spec_stats.num_drafts else None),
        )


class _NullCtx:
    cancelled = False
    id = "local"
