"""Paper-exercise Llama-3-70B on a v5e-64 slice (VERDICT r4 #9).

Two parts:

1. **Sharded compile proof**: AOT-compile the production decode step at
   70B LAYER SHAPES (hidden 8192, heads 64/8, ffn 28672) over a TP=8
   virtual mesh, depth-reduced to a few scan steps — ``lax.scan`` over
   layers means the compiled program is identical modulo the leading L
   dim, so this validates the 70B shardings without 141 GB of arrays.

2. **Budget + roofline solver**: exact per-chip HBM accounting (weights /
   KV split) and the KV-capacity-coupled decode roofline for every
   (tp, weight dtype, KV dtype) combo — decode throughput on v5e is
   bandwidth-bound, and at ISL 2000 the reachable batch is capped by KV
   residency, which feeds back into how well weight reads amortize.

Prints one JSON line; the markdown table for PERF_NOTES goes to stderr.

Usage: JAX_PLATFORMS=cpu python -m benchmarks.plan_70b [--compile]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

HBM_PER_CHIP = 16e9          # v5e
HBM_BW = 819e9               # bytes/s
RUNTIME_OVERHEAD = 1.5e9     # XLA prealloc, activations, framework slack
ISL, OSL = 2000, 256         # reference harness default workload
AVG_KV = ISL + OSL // 2      # mean resident context during decode


def model_bytes(cfg, dtype_bytes: float) -> int:
    """Exact parameter bytes for the llama3_70b preset."""
    D, F, V, L = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size, cfg.num_layers
    H, KV, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    per_layer = (D * H * hd + 2 * D * KV * hd + H * hd * D  # q k v o
                 + 3 * D * F                                # gate up down
                 + 2 * D)                                   # norms (f32-ish, ~0)
    total = L * per_layer + 2 * V * D + D                   # embed + head + norm
    return int(total * dtype_bytes)


def kv_bytes_per_token_per_chip(cfg, tp: int, kv_dtype_bytes: float) -> float:
    """K+V bytes one context token occupies on ONE chip (KV heads shard
    over tp; tp > num_kv_heads replicates heads, capping the win)."""
    heads_per_chip = max(cfg.num_kv_heads / tp, 1.0)
    scale = 4.0 / 16 if kv_dtype_bytes == 1 else 0.0  # int8: f32 scale per (slot, head)
    return 2 * cfg.num_layers * heads_per_chip * (cfg.head_dim * kv_dtype_bytes + scale)


def solve(cfg, tp: int, w_bytes: float, kv_b: float) -> dict:
    """Per-worker batch the HBM budget allows, and the decode roofline at
    that batch. Returns Nones when weights alone do not fit."""
    w_per_chip = model_bytes(cfg, w_bytes) / tp
    kv_room = HBM_PER_CHIP - RUNTIME_OVERHEAD - w_per_chip
    if kv_room <= 0:
        return {"fits": False, "weights_gb_chip": round(w_per_chip / 1e9, 1)}
    kvpt = kv_bytes_per_token_per_chip(cfg, tp, kv_b)
    max_tokens = int(kv_room / kvpt)
    batch = max_tokens // (ISL + OSL)  # each seq holds its full context
    if batch == 0:
        return {"fits": False, "weights_gb_chip": round(w_per_chip / 1e9, 1),
                "note": "KV room < one sequence"}
    # bandwidth-bound step: weights once + every seq's context once
    step_bytes = w_per_chip + batch * AVG_KV * kvpt
    step_s = step_bytes / HBM_BW
    tok_s_worker = batch / step_s
    return {
        "fits": True,
        "weights_gb_chip": round(w_per_chip / 1e9, 1),
        "kv_room_gb_chip": round(kv_room / 1e9, 1),
        "kv_bytes_per_tok_chip": int(kvpt),
        "max_batch_per_worker": batch,
        "step_ms_roofline": round(step_s * 1e3, 1),
        "tok_s_per_chip_roofline": int(tok_s_worker / tp),
        "tok_s_per_chip_at_60pct": int(0.6 * tok_s_worker / tp),
    }


#: the north-star topology on a v5e-64 slice (docs/PERF_NOTES.md "Hub
#: ceiling vs the 70B fleet"): 2 prefill workers + 6 decode workers, TP=8
#: each — 64 chips total. The combo is the solver's best-fitting config
#: (int4-g32 weights + int8 KV: the only pair with real batch headroom).
PLACEMENT_PREFILL_WORKERS = 2
PLACEMENT_DECODE_WORKERS = 6
PLACEMENT_TP = 8
PLACEMENT_COMBO = "tp8_wint4_kvint8"

#: measured hub ceilings the placement is checked against (PERF_NOTES):
#: ~11.7k rpc/s for non-stream hub ops, 119.5k stored blocks/s on the
#: per-request-batched event path, vs the fleet's ~53k blocks/s demand
HUB_RPC_CEILING_PER_S = 11_700
HUB_BLOCKS_CEILING_PER_S = 119_500
HUB_BLOCKS_REQUIRED_PER_S = 53_000


def placement(combo: str = PLACEMENT_COMBO) -> dict:
    """The solved north-star placement as one machine-readable document.

    This is what ``--emit-placement`` prints and what
    ``benchmarks/flagship_drive.py`` instantiates as a mocker fleet —
    the drive consumes the plan instead of re-deriving worker counts,
    step timings, and batch bounds by hand."""
    from dynamo_tpu.engine.config import ModelConfig

    cfg = ModelConfig.llama3_70b()
    w_bytes = {"bf16": 2.0, "int8": 1.0, "int4": 0.5}
    kv_bytes = {"bf16": 2.0, "int8": 1.0}
    # combo key grammar: tp{N}_w{dtype}_kv{dtype}
    tp_s, w_s, kv_s = combo.split("_")
    tp = int(tp_s[2:])
    solved = solve(cfg, tp, w_bytes[w_s[1:]], kv_bytes[kv_s[2:]])
    if not solved.get("fits"):
        raise ValueError(f"placement combo {combo} does not fit on v5e")
    # per-request stored-block math at the reference workload (PERF_NOTES):
    # prefill mints ceil(ISL/16) blocks per request; decode one block per
    # 16 generated tokens
    block = 16
    decode_tok_s = solved["tok_s_per_chip_roofline"] * tp \
        * PLACEMENT_DECODE_WORKERS
    req_s = decode_tok_s / OSL
    stored_blocks_s = int(req_s * math.ceil(ISL / block)
                          + decode_tok_s / block)
    return {
        "model": "llama3-70b",
        "slice": "v5e-64",
        "workload": {"isl": ISL, "osl": OSL},
        "combo": combo,
        "prefill": {"workers": PLACEMENT_PREFILL_WORKERS, "tp": tp,
                    **solved},
        "decode": {"workers": PLACEMENT_DECODE_WORKERS, "tp": tp,
                   **solved},
        "fleet": {
            "workers": PLACEMENT_PREFILL_WORKERS + PLACEMENT_DECODE_WORKERS,
            "chips": (PLACEMENT_PREFILL_WORKERS
                      + PLACEMENT_DECODE_WORKERS) * tp,
            "decode_tok_s": int(decode_tok_s),
            "request_rate_per_s": round(req_s, 1),
            "stored_blocks_per_s": stored_blocks_s,
        },
        "hub": {
            "rpc_ceiling_per_s": HUB_RPC_CEILING_PER_S,
            "blocks_ceiling_per_s": HUB_BLOCKS_CEILING_PER_S,
            "blocks_required_per_s": HUB_BLOCKS_REQUIRED_PER_S,
        },
    }


#: ceiling on the quantized combo's REAL bandwidth demand relative to the
#: solver's analytic roofline (quant_metrics): f32 group scales on int4-g32
#: weights cost 4/32 = 0.125 B/element over the 0.5 B/element payload, so
#: ~1.15× is the honest layout tax; past 1.25 the layout has regressed
#: (scales stored wide, a leaf fallen back to full width, ...)
QUANT_HBM_UTIL_CEILING = 1.25

#: the materialization guard (the §2 risk in docs/PERF_NOTES.md): a
#: grouped dequant chain that materializes full-width weight copies would
#: ADD gigabytes of temp to the 2-layer TP8 step (w_down alone is 0.94 GB
#: f32) — so the quantized program's temp bytes must stay BELOW the bf16
#: program's, never above. Measured on CPU AOT: 0.526 GB quant vs
#: 0.975 GB bf16.
QUANT_TEMP_RATIO_CEILING = 1.05


def compile_proof(tp: int = 8, layers: int = 2, quantization=None,
                  kv_int8: bool = False) -> dict:
    """AOT-compile the decode step at 70B layer shapes over a TP mesh.

    ``quantization``/``kv_int8`` lower the step against the ABSTRACT
    quantized param tree (engine/quant.quantize_params_abstract) and the
    int8 paged-KV pytree — the solved ``tp8_wint4_kvint8`` placement
    proven to lower, shard, and stay under the no-materialization temp
    ceiling without 141 GB of arrays."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={tp}").strip()
    import functools

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")
    from dynamo_tpu.engine import model as M
    from dynamo_tpu.engine.cache import tree_nbytes
    from dynamo_tpu.engine.config import ModelConfig
    from dynamo_tpu.parallel import MeshConfig, make_mesh

    full = ModelConfig.llama3_70b()
    cfg = ModelConfig(**{**full.__dict__, "num_layers": layers})
    mesh = make_mesh(MeshConfig(dp=1, sp=1, tp=tp))
    block_size, num_blocks, B, W = 16, 64, 8, 16

    params = jax.eval_shape(functools.partial(M.init_params, cfg),
                            jax.random.key(0))
    sh_params = M.param_shardings(cfg, mesh)
    if quantization is not None:
        from dynamo_tpu.engine.quant import (
            quant_shardings, quantize_params_abstract,
        )
        params = quantize_params_abstract(params, quantization)
        sh_params = quant_shardings(sh_params, params)
    slots = num_blocks * block_size
    if kv_int8:
        kc = {"q": jax.ShapeDtypeStruct(
                  (cfg.num_layers, slots, cfg.num_kv_heads, cfg.head_dim),
                  jnp.int8),
              "s": jax.ShapeDtypeStruct(
                  (cfg.num_layers, slots, cfg.num_kv_heads), jnp.float32)}
        sh_cache = M.cache_shardings(mesh, cfg, quant=True)
    else:
        kc = jax.ShapeDtypeStruct((cfg.num_layers, slots,
                                   cfg.num_kv_heads, cfg.head_dim),
                                  jnp.dtype(cfg.dtype))
        sh_cache = M.cache_shardings(mesh, cfg)
    args = (
        params,
        jax.ShapeDtypeStruct((B, 1), jnp.int32),      # tokens
        jax.ShapeDtypeStruct((B, 1), jnp.int32),      # positions
        jax.ShapeDtypeStruct((B, 1), jnp.int32),      # slot_map
        jax.ShapeDtypeStruct((B, W), jnp.int32),      # block_tables
        jax.ShapeDtypeStruct((B,), jnp.int32),        # kv_lens
        jax.ShapeDtypeStruct((B,), jnp.int32),        # last_idx
        kc, kc,
    )
    fn = functools.partial(M.forward, cfg=cfg, block_size=block_size,
                           mesh=mesh)
    bs = M.batch_shardings(mesh)
    in_sh = (sh_params, bs["tokens"], bs["positions"], bs["slot_map"],
             bs["block_tables"], bs["kv_lens"], bs["last_idx"],
             sh_cache, sh_cache)
    with mesh:
        lowered = jax.jit(fn, in_shardings=in_sh).lower(*args)
        compiled = lowered.compile()
    ma = compiled.memory_analysis()
    return {
        "tp": tp, "layers": layers,
        "quantization": quantization, "kv_int8": kv_int8,
        "params_bytes": int(tree_nbytes(params)),
        "argument_gb": round(ma.argument_size_in_bytes / 1e9, 2),
        "temp_gb": round(ma.temp_size_in_bytes / 1e9, 3),
        "output_gb": round(ma.output_size_in_bytes / 1e9, 3),
    }


def quant_metrics(combo: str = PLACEMENT_COMBO) -> dict:
    """Ground-truth HBM accounting for a quantized combo from the REAL
    quantized param tree (abstract — shapes only, full 80-layer depth),
    against the solver's analytic estimate.

    ``kernel_hbm_util_v5e`` is the fraction of v5e peak bandwidth the
    placement needs to hit its solved roofline tok/s once the real layout
    tax (f32 group scales, non-divisible leaves kept wide) is counted:
    1.0 = the analytic plan was exact, > QUANT_HBM_UTIL_CEILING = the
    quantized layout regressed and the plan is infeasible."""
    import functools

    import jax

    from dynamo_tpu.engine import model as M
    from dynamo_tpu.engine.cache import tree_nbytes
    from dynamo_tpu.engine.config import ModelConfig
    from dynamo_tpu.engine.quant import quantize_params_abstract

    cfg = ModelConfig.llama3_70b()
    tp_s, w_s, kv_s = combo.split("_")
    tp = int(tp_s[2:])
    wname, kvname = w_s[1:], kv_s[2:]
    w_bytes = {"bf16": 2.0, "int8": 1.0, "int4": 0.5}[wname]
    kv_b = {"bf16": 2.0, "int8": 1.0}[kvname]
    solved = solve(cfg, tp, w_bytes, kv_b)
    params = jax.eval_shape(functools.partial(M.init_params, cfg),
                            jax.random.key(0))
    spec = {"int8": "int8", "int4": "int4-g32"}.get(wname)
    if spec is not None:
        params = quantize_params_abstract(params, spec)
    pb = int(tree_nbytes(params))
    out = {"combo": combo, "quant_spec": spec, "params_bytes": pb,
           "weights_gb_chip_actual": round(pb / tp / 1e9, 2),
           "fits": bool(solved.get("fits"))}
    if not solved.get("fits"):
        return out
    # the step the solver planned, re-costed with the real weight bytes
    kvpt = kv_bytes_per_token_per_chip(cfg, tp, kv_b)
    batch = solved["max_batch_per_worker"]
    step_bytes = pb / tp + batch * AVG_KV * kvpt
    planned_step_s = solved["step_ms_roofline"] / 1e3
    out["kernel_hbm_util_v5e"] = round(
        step_bytes / (planned_step_s * HBM_BW), 3)
    out["tok_s_per_chip_roofline_actual"] = int(
        batch / (step_bytes / HBM_BW) / tp)
    return out


def assert_quant(run_compile: bool = False) -> dict:
    """The ``--assert-quant`` exit gate: the solved quantized placement
    must fit, its real-layout bandwidth demand must stay under
    QUANT_HBM_UTIL_CEILING, and (with ``run_compile``) the quantized step
    must AOT-lower with temp bytes under the no-materialization ceiling.
    The bench quant phase runs the solver half of this; the compile half
    also runs as a test (tests/test_quant_serving.py)."""
    proofs = None
    if run_compile:
        # BEFORE any other jax use: compile_proof sets the host-device
        # XLA flag, which only takes effect if jax is uninitialized
        proofs = (compile_proof(quantization="int4-g32", kv_int8=True),
                  compile_proof())
    qm = quant_metrics(PLACEMENT_COMBO)
    ok = qm["fits"] and qm.get(
        "kernel_hbm_util_v5e", 99.0) <= QUANT_HBM_UTIL_CEILING
    out = dict(qm)
    if proofs is not None:
        proof_q, proof_bf16 = proofs
        out["compile_proof"] = proof_q
        out["compile_proof_bf16"] = proof_bf16
        # materialization guard: wide dequant copies would push quant temp
        # past bf16 temp (see QUANT_TEMP_RATIO_CEILING note)
        ok = (ok and proof_q["temp_gb"]
              <= proof_bf16["temp_gb"] * QUANT_TEMP_RATIO_CEILING)
    out["quant_ok"] = bool(ok)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--compile", action="store_true",
                    help="also AOT-compile the sharded step (slow on 1 core)")
    ap.add_argument("--emit-placement", action="store_true",
                    help="print ONLY the solved north-star placement "
                         "(2xTP8 prefill + 6xTP8 decode) as JSON and exit")
    ap.add_argument("--combo", default=PLACEMENT_COMBO,
                    help=f"placement combo key (default {PLACEMENT_COMBO})")
    ap.add_argument("--assert-quant", action="store_true",
                    help="exit 1 unless the solved quantized placement "
                         "(tp8_wint4_kvint8) fits with real-layout bytes "
                         "under the bandwidth ceiling; add --compile to "
                         "also AOT-lower the quantized step and gate its "
                         "temp bytes (no-materialization proof)")
    cli = ap.parse_args()

    if cli.emit_placement:
        print(json.dumps(placement(cli.combo)), flush=True)
        return

    if cli.assert_quant:
        res = assert_quant(run_compile=cli.compile)
        print(json.dumps(res), flush=True)
        sys.exit(0 if res["quant_ok"] else 1)

    from dynamo_tpu.engine.config import ModelConfig
    cfg = ModelConfig.llama3_70b()

    combos = {}
    for tp in (8, 16):
        for wname, wb in (("bf16", 2.0), ("int8", 1.0), ("int4", 0.5)):
            for kname, kb in (("bf16", 2.0), ("int8", 1.0)):
                combos[f"tp{tp}_w{wname}_kv{kname}"] = solve(cfg, tp, wb, kb)

    out = {
        "model": "llama3-70b",
        "workload": f"ISL={ISL} OSL={OSL} (benchmarking.md:33)",
        "params_b": round(model_bytes(cfg, 1.0) / 1e9, 1),
        "combos": combos,
    }
    if cli.compile:
        out["compile_proof"] = compile_proof()

    # human table to stderr
    print("| config | w GB/chip | KV room | max B/worker | roofline tok/s/chip | @60% |",
          file=sys.stderr)
    print("|---|---|---|---|---|---|", file=sys.stderr)
    for k, v in combos.items():
        if not v.get("fits"):
            print(f"| {k} | {v['weights_gb_chip']} | DOES NOT FIT | - | - | - |",
                  file=sys.stderr)
        else:
            print(f"| {k} | {v['weights_gb_chip']} | {v['kv_room_gb_chip']} | "
                  f"{v['max_batch_per_worker']} | {v['tok_s_per_chip_roofline']} | "
                  f"{v['tok_s_per_chip_at_60pct']} |", file=sys.stderr)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
