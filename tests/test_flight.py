"""Fleet flight recorder (docs/observability.md "Flight recorder"):
ring-bounded memory, inline anomaly tagging, fleet fan-out with dead-worker
drop, JSONL round-trip, mocker parity, trace head-sampling, hub event
instrumentation, engine compile visibility, and tier occupancy gauges."""

import asyncio
import json

import msgpack
import pytest

from dynamo_tpu.observability import (
    FlightRecorder,
    StepRecord,
    Tracer,
    fetch_fleet_steps,
    serve_flight,
    trace_sampled,
)
from dynamo_tpu.observability.flight import (
    FLIGHT_PREFIX,
    TAG_COMPILE_STEADY,
    TAG_EMPTY,
    TAG_PREEMPT_STORM,
    TAG_SLOW,
    TAG_STARVED,
    register_recorder,
    unregister_recorder,
)
from dynamo_tpu.runtime.context import Context

pytestmark = pytest.mark.anyio


def make_recorder(**kw) -> FlightRecorder:
    kw.setdefault("service", "test")
    kw.setdefault("enabled", True)
    return FlightRecorder(**kw)


# ------------------------------------------------------------- ring + tags


def test_ring_bounded_under_10k_steps():
    rec = make_recorder(capacity=512)
    for i in range(10_000):
        rec.record("ragged", 2.0, decode_rows=4, chunk_tokens=8,
                   kv_tiers={"g1": i % 7})
    assert len(rec) == 512
    snap = rec.snapshot()
    assert len(snap) == 512
    # the ring keeps the NEWEST records and the seq keeps counting
    assert snap[-1]["seq"] == 10_000
    assert snap[0]["seq"] == 10_000 - 512 + 1
    assert rec.summary()["steps_total"] == 10_000
    # baseline/storm windows are bounded too (no unbounded growth)
    assert all(len(b[0]) <= 256 for b in rec._base.values())
    assert len(rec._storm) <= 32


def test_disabled_recorder_records_nothing():
    rec = make_recorder(enabled=False)
    assert rec.record("ragged", 1.0) is None
    assert len(rec) == 0


def test_slow_step_tag_needs_baseline_and_sigma():
    rec = make_recorder()
    for _ in range(40):
        r = rec.record("ragged", 5.0, decode_rows=1)
        assert TAG_SLOW not in r.tags  # steady baseline: no false tags
    slow = rec.record("ragged", 120.0, decode_rows=1)
    assert TAG_SLOW in slow.tags
    # the outlier joined the baseline AFTER tagging, not before
    assert rec.anomaly_counts[TAG_SLOW] == 1
    # too few samples → never tags (σ of 3 samples is noise)
    fresh = make_recorder()
    fresh.record("ragged", 1.0)
    r = fresh.record("ragged", 500.0)
    assert TAG_SLOW not in r.tags


def test_slow_step_baseline_is_per_kind():
    """A routine 30 ms prefill after a stretch of ~1 ms pipelined decode
    steps is NOT slow — a pooled baseline would tag every burst boundary."""
    rec = make_recorder()
    for _ in range(40):
        rec.record("decode_pipe", 1.0, decode_rows=4)
    r = rec.record("ragged", 30.0, prefill_chunks=1, chunk_tokens=64)
    assert TAG_SLOW not in r.tags  # no ragged baseline yet
    for _ in range(20):
        rec.record("ragged", 30.0, prefill_chunks=1, chunk_tokens=64)
    ok = rec.record("ragged", 30.2, prefill_chunks=1, chunk_tokens=64)
    assert TAG_SLOW not in ok.tags  # within the 0.5 ms jitter floor
    slow = rec.record("ragged", 400.0, prefill_chunks=1, chunk_tokens=64)
    assert TAG_SLOW in slow.tags
    # the decode baseline still catches ITS OWN outliers
    slow_d = rec.record("decode_pipe", 50.0, decode_rows=4)
    assert TAG_SLOW in slow_d.tags


def test_compile_steady_tag_and_warmup_grace():
    rec = make_recorder()
    rec.steady_after = 10
    early = rec.record("ragged", 50.0, compile_s=0.5, compile_sig="ragged:64")
    assert "compile" in early.tags and TAG_COMPILE_STEADY not in early.tags
    for _ in range(12):
        rec.record("ragged", 2.0)
    late = rec.record("ragged", 50.0, compile_s=0.5, compile_sig="ragged:8")
    assert TAG_COMPILE_STEADY in late.tags


def test_preempt_storm_tag_rolling_window():
    rec = make_recorder()
    rec.storm_threshold = 4
    # sparse preemptions never tag
    for i in range(60):
        r = rec.record("ragged", 2.0,
                       preempt_recompute=1 if i % 40 == 0 else 0)
        assert TAG_PREEMPT_STORM not in r.tags
    # a burst inside the window does; preempt-free records in between
    # do NOT get the tag (the tag marks steps that preempted)
    tagged = []
    for i in range(6):
        r = rec.record("ragged", 2.0, preempt_swap=1)
        tagged.append(TAG_PREEMPT_STORM in r.tags)
    assert any(tagged)
    calm = rec.record("ragged", 2.0)
    assert TAG_PREEMPT_STORM not in calm.tags


def test_starved_and_empty_tags():
    rec = make_recorder()
    r = rec.record("ragged", 2.0, decode_rows=3, starved_decode=2)
    assert TAG_STARVED in r.tags
    e = rec.record("empty", 50.0, waiting=4)
    assert TAG_EMPTY in e.tags
    # empty bubbles stay out of the slow-step baselines
    assert "empty" not in rec._base
    assert sum(len(b[0]) for b in rec._base.values()) == 1


def test_summary_math():
    rec = make_recorder()
    for i in range(10):
        rec.record("ragged", float(i + 1), decode_rows=2, chunk_tokens=3,
                   waiting=1, running=2, kv_tiers={"g1": 5, "g2": 1})
    s = rec.summary()
    assert s["steps_total"] == 10
    assert s["tokens_in_ring"] == 50
    # the shared interpolated estimator (observability/stats.quantile):
    # p50 of 1..10 interpolates between the 5th and 6th order statistics
    assert s["wall_p50_ms"] == 5.5
    assert s["wall_p95_ms"] == 9.55
    assert s["kv_tiers"] == {"g1": 5, "g2": 1}
    assert s["waiting"] == 1 and s["running"] == 2


# ------------------------------------------------------------ JSONL export


def test_jsonl_export_round_trips(tmp_path):
    rec = make_recorder()
    rec.record("ragged", 3.25, decode_rows=2, prefill_chunks=1,
               chunk_tokens=7, padded_tokens=4, compile_s=0.5,
               compile_sig="ragged:64", preempt_swap=1, starved_decode=1,
               kv_tiers={"g1": 3, "g4": 2}, qos_mix={"interactive": 2})
    rec.record("empty", 12.0, waiting=3)
    path = tmp_path / "steps.jsonl"
    n = rec.export_jsonl(str(path))
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert n == len(lines) == 2
    back = StepRecord.from_dict(lines[0])
    assert back.kind == "ragged" and back.wall_ms == 3.25
    assert back.decode_rows == 2 and back.chunk_tokens == 7
    assert back.compile_sig == "ragged:64" and back.preempt_swap == 1
    assert back.kv_tiers == {"g1": 3, "g4": 2}
    assert back.qos_mix == {"interactive": 2}
    assert "compile" in back.tags
    assert StepRecord.from_dict(lines[1]).kind == "empty"


def test_streaming_jsonl_env(tmp_path, monkeypatch):
    path = tmp_path / "live.jsonl"
    monkeypatch.setenv("DYN_STEP_JSONL", str(path))
    rec = make_recorder()
    rec.record("ragged", 1.0, decode_rows=1)
    rec.record("ragged", 2.0, decode_rows=1)
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert [d["seq"] for d in lines] == [1, 2]


# ----------------------------------------------------------- fleet fan-out


async def test_fleet_fanout_merges_and_drops_dead_worker():
    from dynamo_tpu.runtime import DistributedRuntime

    rt = await DistributedRuntime.create()
    rec = make_recorder(service="workerA")
    for _ in range(20):
        rec.record("mock", 2.0, decode_rows=1, kv_tiers={"g1": 4})
    name = register_recorder("workerA", rec)
    try:
        handle = await serve_flight(rt)
        # a dead worker: discovery key present, nothing serving its subject
        await rt.plane.kv_put(
            FLIGHT_PREFIX + "deadbeef",
            msgpack.packb({"subject": "flight-gone", "service": "dead"}))
        out = await fetch_fleet_steps(rt.plane, n=5, timeout=0.3)
        assert len(out) == 1  # dead worker dropped, live one served
        key = next(iter(out))
        assert key.endswith("/workerA")
        assert out[key]["summary"]["steps_total"] == 20
        assert len(out[key]["steps"]) == 5
        # summary-only query ships no step payloads
        out0 = await fetch_fleet_steps(rt.plane, n=0, timeout=0.3)
        assert "steps" not in out0[key]
        await handle.stop()
        assert await fetch_fleet_steps(rt.plane, timeout=0.3) == {}
    finally:
        unregister_recorder(name)
        await rt.shutdown()


async def test_frontend_fleet_steps_route():
    """GET /v1/fleet/steps serves the fan-out through the HTTP frontend."""
    import aiohttp

    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.llm.discovery import ModelManager
    from dynamo_tpu.runtime import DistributedRuntime

    rt = await DistributedRuntime.create()
    rec = make_recorder(service="w0")
    rec.record("mock", 1.0, decode_rows=1)
    name = register_recorder("w0", rec)
    svc = HttpService(ModelManager(), host="127.0.0.1", port=0, runtime=rt)
    try:
        handle = await serve_flight(rt)
        port = await svc.start()
        async with aiohttp.ClientSession() as s:
            async with s.get(
                    f"http://127.0.0.1:{port}/v1/fleet/steps?n=3") as resp:
                assert resp.status == 200
                body = await resp.json()
        assert body["count"] == 1
        entry = next(iter(body["workers"].values()))
        assert entry["summary"]["steps_total"] == 1
        assert len(entry["steps"]) == 1
        await handle.stop()
    finally:
        unregister_recorder(name)
        await svc.stop()
        await rt.shutdown()


# ---------------------------------------------------------- mocker parity


async def test_mocker_flight_parity():
    """The mocker's simulated steps append the same record shape the real
    engine does (fleet tests see one timeline model)."""
    from dynamo_tpu.mocker.engine import MockEngine, MockEngineArgs
    from dynamo_tpu.protocols import (PreprocessedRequest, SamplingOptions,
                                      StopConditions)

    eng = await MockEngine(MockEngineArgs(
        num_gpu_blocks=128, block_size=4, max_num_seqs=4,
        max_num_batched_tokens=64, speedup_ratio=100.0)).start()
    try:
        req = PreprocessedRequest(
            model="m", token_ids=list(range(1, 30)),
            stop_conditions=StopConditions(max_tokens=8, ignore_eos=True),
            sampling_options=SamplingOptions(), eos_token_ids=[2])
        ctx = Context()
        n = 0
        async for out in eng.generate(req, ctx):
            n += len(out.get("token_ids") or [])
            if out.get("finish_reason"):
                break
        assert n >= 8
        snap = eng.flight.snapshot()
        assert snap, "mocker recorded no flight steps"
        kinds = {d["kind"] for d in snap}
        assert "mock" in kinds
        steps = [d for d in snap if d["kind"] == "mock"]
        assert any(d["chunk_tokens"] > 0 for d in steps)  # prefill visible
        assert any(d["decode_rows"] > 0 for d in steps)   # decode visible
        assert all("kv_tiers" in d for d in steps)
        s = eng.flight.summary()
        assert s["steps_total"] == len(snap)
    finally:
        await eng.stop()


# --------------------------------------------------------- trace sampling


def test_trace_sampling_deterministic_and_gating(monkeypatch):
    ids = [f"req-{i}" for i in range(400)]
    monkeypatch.setenv("DYN_TRACE_SAMPLE", "0.5")
    first = [trace_sampled(i) for i in ids]
    assert first == [trace_sampled(i) for i in ids]  # deterministic
    assert 0.3 < sum(first) / len(first) < 0.7
    # rate 0: every span degrades to the noop (bounded overhead)
    monkeypatch.setenv("DYN_TRACE_SAMPLE", "0")
    tracer = Tracer(service="t", capacity=8)
    ctx = Context()
    with tracer.span("http.request", ctx) as sp:
        sp.set(a=1)
    assert tracer.all_spans() == []
    assert tracer.record_hop(ctx, ctx.child_traceparent()).span_id == ""
    # rate 1 (and unset): everything records
    monkeypatch.setenv("DYN_TRACE_SAMPLE", "1.0")
    with tracer.span("http.request", ctx):
        pass
    assert len(tracer.all_spans()) == 1
    # malformed rate falls back to record-everything, not crash
    monkeypatch.setenv("DYN_TRACE_SAMPLE", "bogus")
    with tracer.span("http.request", ctx):
        pass
    assert len(tracer.all_spans()) == 2


async def test_unsampled_trace_http_response(monkeypatch):
    """/v1/traces/{id} says "not sampled" instead of 404 when the id was
    head-sampled out (the operator must be able to tell the difference)."""
    import aiohttp

    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.llm.discovery import ModelManager

    # find an id the 0.001-rate sampler drops (virtually all of them)
    monkeypatch.setenv("DYN_TRACE_SAMPLE", "0.001")
    rid = next(f"r-{i}" for i in range(1000)
               if not trace_sampled(f"r-{i}", 0.001))
    svc = HttpService(ModelManager(), host="127.0.0.1", port=0)
    try:
        port = await svc.start()
        async with aiohttp.ClientSession() as s:
            async with s.get(
                    f"http://127.0.0.1:{port}/v1/traces/{rid}") as resp:
                assert resp.status == 200
                body = await resp.json()
        assert body["sampled"] is False
        assert "DYN_TRACE_SAMPLE" in body["reason"]
        # a SAMPLED id with no spans still 404s (trace expired ≠ unsampled)
        hit = next(f"r-{i}" for i in range(1000)
                   if trace_sampled(f"r-{i}", 0.001))
        async with aiohttp.ClientSession() as s:
            async with s.get(
                    f"http://127.0.0.1:{port}/v1/traces/{hit}") as resp:
                assert resp.status == 404
    finally:
        await svc.stop()


# ------------------------------------------------------------- hub metrics


async def test_hub_event_counters_and_publish_latency():
    from dynamo_tpu.runtime.control_plane import LocalControlPlane

    plane = LocalControlPlane()
    await plane.kv_put("k1", b"v")
    await plane.kv_delete("k1")
    await plane.publish("subj", b"x")
    await plane.stream_publish("st", b"y")
    await plane.queue_push("q", b"z")
    stats = await plane.hub_stats()
    ev = stats["events"]
    assert ev["kv_put"] == 1 and ev["kv_delete"] == 1
    assert ev["publish"] == 1 and ev["stream_publish"] == 1
    assert ev["queue_push"] == 1
    pub = stats["publish_seconds"]
    assert pub["count"] == 2 and pub["sum"] > 0
    assert pub["buckets"]["+Inf"] == 2
    await plane.close()


async def test_hub_stats_over_tcp_and_metrics_render():
    from dynamo_tpu.metrics.main import MetricsService
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.control_plane import (
        ControlPlaneServer, RemoteControlPlane,
    )

    server = ControlPlaneServer("127.0.0.1", 0)
    addr = await server.start()
    plane = await RemoteControlPlane(addr).connect()
    try:
        await plane.publish("some.subject", b"p")
        stats = await plane.hub_stats()
        assert stats["events"]["publish"] == 1
        rt = await DistributedRuntime.create(plane=plane, owns_plane=False)
        svc = MetricsService(rt)
        text = svc.render(prefill_queue_depth=0, hub=stats)
        assert '# TYPE dynamo_hub_events_total counter' in text
        assert 'dynamo_hub_events_total{kind="publish"} 1' in text
        assert "# TYPE dynamo_hub_publish_seconds histogram" in text
        assert "dynamo_hub_publish_seconds_count 1" in text
        await rt.shutdown()
    finally:
        await plane.close()
        await server.stop()


# ------------------------------------------- engine parity + compile + tiers


@pytest.fixture(scope="module")
def tiny_engine_cfg():
    from dynamo_tpu.engine.config import EngineArgs, ModelConfig

    return ModelConfig.tiny(), dict(
        block_size=4, num_blocks=64, max_num_seqs=4,
        max_num_batched_tokens=64, max_model_len=256,
        enable_prefix_caching=False)


async def test_engine_flight_records_and_compile_visibility(tiny_engine_cfg):
    """A real (tiny-cpu) engine step appends tagged records, counts its
    post-warmup jit traces, and reports tier occupancy."""
    from dynamo_tpu.engine.config import EngineArgs
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.protocols import (PreprocessedRequest, SamplingOptions,
                                      StopConditions)

    cfg, base = tiny_engine_cfg
    eng = AsyncJaxEngine(cfg, EngineArgs(**base))
    try:
        req = PreprocessedRequest(
            model="m", token_ids=list(range(1, 30)),
            stop_conditions=StopConditions(max_tokens=6, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0))
        n = 0
        async for out in eng.generate(req):
            n += len(out.token_ids)
        assert n == 6
        snap = eng.flight.snapshot()
        assert snap
        first = snap[0]
        assert first["kind"] == "ragged" and first["chunk_tokens"] == 29
        assert "compile" in first["tags"]  # cold engine: first trace
        assert first["compile_s"] > 0 and first["compile_sig"]
        assert first["dispatch_ms"] > 0
        assert set(first["kv_tiers"]) == {"g1", "g2", "g3", "g4"}
        # compile accounting: the dispatch kinds this run traced
        assert eng.compile_events.get("ragged") == 1
        assert eng.compile_seconds["ragged"] > 0
        # tier occupancy: g1 empty again after the stream finished
        occ = eng.kv_tier_occupancy()
        assert occ["g1"]["blocks"] == 0
        assert occ["g2"] == {"blocks": 0, "bytes": 0}
    finally:
        await eng.close()


async def test_engine_flight_disabled_is_pure_observation(tiny_engine_cfg):
    """DYN_FLIGHT=0 arm: identical token stream, zero records (the bench
    A/B contract in miniature)."""
    from dynamo_tpu.engine.config import EngineArgs
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.protocols import (PreprocessedRequest, SamplingOptions,
                                      StopConditions)

    cfg, base = tiny_engine_cfg

    async def run(flight_on: bool) -> list:
        eng = AsyncJaxEngine(cfg, EngineArgs(**base))
        eng.flight.enabled = flight_on
        req = PreprocessedRequest(
            model="m", token_ids=list(range(1, 20)),
            stop_conditions=StopConditions(max_tokens=8, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0))
        toks = []
        async for out in eng.generate(req):
            toks.extend(out.token_ids)
        recs = len(eng.flight)
        await eng.close()
        return toks, recs

    on_toks, on_recs = await run(True)
    off_toks, off_recs = await run(False)
    assert on_toks == off_toks
    assert on_recs > 0 and off_recs == 0
