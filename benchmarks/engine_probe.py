"""Direct-engine serving probe: AsyncJaxEngine without HTTP/frontend.

The r4 tool that located the serving-vs-kernel gap on real hardware:
reports engine init time and auto block sizing, runs a warmup (compile
set) then a concurrent closed-loop batch, and prints decode tok/s, TTFT
p50, and the engine's per-kind step-trace summary — the numbers to
compare against bench.py's kernel phase.

Usage: python -m benchmarks.engine_probe [--conc 32] [--isl 1024]
       [--osl 64] [--multi-step 16]
(On the shared TPU host: run with everything else idle — see
docs/PERF_NOTES.md "tunnel tax".)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time

import numpy as np


async def amain():
    ap = argparse.ArgumentParser(description="direct engine serving probe")
    ap.add_argument("--arch", default="llama3_1b")
    ap.add_argument("--conc", type=int, default=32)
    ap.add_argument("--isl", type=int, default=1024)
    ap.add_argument("--osl", type=int, default=64)
    ap.add_argument("--multi-step", type=int, default=16)
    ap.add_argument("--kv-cache-dtype", default=None)
    ap.add_argument("--quantization", default=None)
    ap.add_argument("--platform", default=None,
                    help="cpu = force the CPU backend BEFORE first device "
                         "touch (the container sitecustomize pins the axon "
                         "TPU; env vars alone are too late, and a dead "
                         "tunnel wedges init)")
    cli = ap.parse_args()

    if cli.platform:
        import jax

        jax.config.update("jax_platforms", cli.platform)

    from dynamo_tpu.engine.config import EngineArgs
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.models import get_model_config
    from dynamo_tpu.protocols import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )

    cfg = get_model_config(cli.arch)
    args = EngineArgs(
        block_size=16, max_num_seqs=max(64, cli.conc),
        max_num_batched_tokens=2048, max_model_len=cli.isl + cli.osl + 64,
        multi_step_decode=cli.multi_step, use_pallas_attention=True,
        quantization=cli.quantization, kv_cache_dtype=cli.kv_cache_dtype,
        prefill_buckets=(1024, 2048), decode_batch_buckets=(32, 64))
    t0 = time.perf_counter()
    eng = AsyncJaxEngine(cfg, args)
    out = {"init_s": round(time.perf_counter() - t0, 1),
           "num_blocks": eng.num_blocks,
           "kv_capacity_tokens": eng.num_blocks * args.block_size}
    print(json.dumps(out), flush=True)

    rng = np.random.default_rng(0)

    async def run_one(isl, osl, timings):
        req = PreprocessedRequest(
            model="probe",
            token_ids=rng.integers(1, cfg.vocab_size, isl).tolist(),
            sampling_options=SamplingOptions(temperature=0.0),
            stop_conditions=StopConditions(max_tokens=osl, ignore_eos=True))
        t0 = time.perf_counter()
        first, n = None, 0
        async for o in eng.generate(req):
            if first is None:
                first = time.perf_counter() - t0
            n += len(o.token_ids or [])
            if o.finish_reason is not None:
                break
        timings.append((first, n))

    tm = []
    t0 = time.perf_counter()
    await asyncio.gather(*[run_one(cli.isl, 16, tm)
                           for _ in range(cli.conc)])
    print(json.dumps({"warmup_s": round(time.perf_counter() - t0, 1)}),
          flush=True)

    tm = []
    t0 = time.perf_counter()
    await asyncio.gather(*[run_one(cli.isl, cli.osl, tm)
                           for _ in range(cli.conc)])
    wall = time.perf_counter() - t0
    ttfts = sorted(f for f, _ in tm if f is not None)
    out = {
        "decode_tok_s": round(sum(n for _, n in tm) / wall, 1),
        "ttft_p50_ms": round(1000 * ttfts[len(ttfts) // 2], 1),
        "wall_s": round(wall, 1),
        "workload": f"ISL={cli.isl},OSL={cli.osl},conc={cli.conc}",
        "step_trace": eng.step_trace_summary(),
    }
    await eng.close()
    print(json.dumps(out))


if __name__ == "__main__":
    asyncio.run(amain())
