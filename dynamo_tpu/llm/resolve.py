"""Model resolution: local dir / GGUF file / HF hub id → servable artifacts.

Rebuild of the reference's model resolution (ref: lib/llm/src/hub.rs:1-299 +
local_model.rs:1-456 — accepts a local path, a GGUF file, or a HF repo id;
repo ids resolve through the local HF cache before any network). Resolution
order here:

1. existing directory with ``config.json`` → HF checkpoint dir,
2. existing ``*.gguf`` file → GGUF,
3. ``org/name`` repo id → newest snapshot in the HF cache
   (``$HF_HOME``/``~/.cache/huggingface/hub``), else ``huggingface_hub``
   download when the environment allows network.

Every kind answers the same four questions: model config, engine params,
EOS ids, and the tokenizer reference to publish in the MDC.
"""

from __future__ import annotations

import os
from dataclasses import dataclass


@dataclass
class ResolvedModel:
    kind: str  # "hf_dir" | "gguf"
    path: str
    _gguf: object = None  # parsed GGUFFile, cached across the accessors

    @property
    def tokenizer_ref(self) -> str:
        return self.path

    def gguf(self):
        """The parsed GGUFFile, cached — config/eos/params/tokenizer all
        need the (metadata-heavy) parse; one pass serves them all."""
        if self._gguf is None:
            from dynamo_tpu.llm.gguf import GGUFFile

            self._gguf = GGUFFile.parse(self.path)
        return self._gguf

    def config(self):
        if self.kind == "gguf":
            from dynamo_tpu.llm.gguf import config_from_gguf

            return config_from_gguf(self.gguf())
        from dynamo_tpu.engine.config import ModelConfig

        return ModelConfig.from_pretrained(self.path)

    def load_params(self, cfg, dtype=None) -> dict:
        if self.kind == "gguf":
            from dynamo_tpu.llm.gguf import load_gguf_params

            return load_gguf_params(self.gguf(), cfg, dtype)
        from dynamo_tpu.engine.loader import load_hf_params

        return load_hf_params(cfg, self.path, dtype)

    def eos_token_ids(self) -> list[int]:
        if self.kind == "gguf":
            from dynamo_tpu.llm.gguf import eos_ids_from_gguf

            return eos_ids_from_gguf(self.gguf())
        from dynamo_tpu.llm.model_card import resolve_eos_token_ids

        return resolve_eos_token_ids(self.path)


def _hf_cache_dir() -> str:
    if os.environ.get("HF_HUB_CACHE"):
        return os.environ["HF_HUB_CACHE"]
    home = os.environ.get("HF_HOME",
                          os.path.expanduser("~/.cache/huggingface"))
    return os.path.join(home, "hub")


def _cached_snapshot(repo_id: str):
    """Newest complete snapshot of a repo in the local HF cache, or None."""
    repo_dir = os.path.join(_hf_cache_dir(),
                            "models--" + repo_id.replace("/", "--"))
    snaps = os.path.join(repo_dir, "snapshots")
    if not os.path.isdir(snaps):
        return None
    candidates = [os.path.join(snaps, d) for d in os.listdir(snaps)]
    candidates = [d for d in candidates
                  if os.path.exists(os.path.join(d, "config.json"))]
    if not candidates:
        return None
    return max(candidates, key=os.path.getmtime)


def resolve_model(ref: str, allow_download: bool = True) -> ResolvedModel:
    """Resolve a model reference to local artifacts (dir path, GGUF file,
    or ``org/name`` hub id). Raises FileNotFoundError with the attempted
    interpretations when nothing matches."""
    if os.path.isdir(ref):
        if os.path.exists(os.path.join(ref, "config.json")):
            return ResolvedModel("hf_dir", ref)
        ggufs = sorted(f for f in os.listdir(ref) if f.endswith(".gguf"))
        if len(ggufs) > 1:
            # prefer an unquantized export (quantized variants refuse to
            # load), THEN reject if only shards survive — a valid
            # single-file export must win over leftover shard files
            full = [f for f in ggufs
                    if any(t in f.lower() for t in ("f32", "f16", "bf16"))]
            ggufs = full or ggufs
            single = [f for f in ggufs if "-of-" not in f]
            if not single:
                raise FileNotFoundError(
                    f"{ref}: only sharded GGUF exports found; point "
                    "--model-path at a single-file export")
            ggufs = single
        if ggufs:
            return ResolvedModel("gguf", os.path.join(ref, ggufs[0]))
        raise FileNotFoundError(
            f"{ref}: directory has neither config.json nor a .gguf file")
    if os.path.isfile(ref):
        if ref.endswith(".gguf"):
            return ResolvedModel("gguf", ref)
        raise FileNotFoundError(f"{ref}: only .gguf files are servable directly")
    if "/" in ref and not ref.startswith((".", "/")):
        snap = _cached_snapshot(ref)
        if snap is not None:
            return ResolvedModel("hf_dir", snap)
        if allow_download:
            try:
                from huggingface_hub import snapshot_download

                path = snapshot_download(ref)
                return ResolvedModel("hf_dir", path)
            except Exception as e:
                raise FileNotFoundError(
                    f"{ref}: not in the HF cache ({_hf_cache_dir()}) and "
                    f"download failed ({e!r})") from None
        raise FileNotFoundError(
            f"{ref}: not in the HF cache ({_hf_cache_dir()}) and downloads "
            "are disabled")
    raise FileNotFoundError(
        f"{ref}: not a checkpoint dir, .gguf file, or org/name repo id")
