"""The native TPU engine: JAX/XLA/Pallas token generation.

This is the TPU build's equivalent of the reference's delegated GPU engines
(vLLM/SGLang/TRT-LLM — ref: components/backends/*): a paged-KV, continuously
batched, pjit-sharded inference engine that plugs into the distributed runtime
exactly like the reference's Python backends plug into theirs (register_llm +
serve_endpoint + KV events + ForwardPassMetrics).

Layout:
- config.py    — ModelConfig / EngineArgs
- model.py     — llama-family forward pass over a paged KV cache (scan layers)
- sampling.py  — on-device sampling (greedy / temperature / top-k / top-p)
- cache.py     — device cache allocation + host-side block pool & prefix cache
- scheduler.py — continuous batching: admission, chunked prefill, decode batch
- engine.py    — AsyncJaxEngine: the async generate() loop + KV events
- loader.py    — HF checkpoint loading / random init
"""

from dynamo_tpu.engine.config import EngineArgs, ModelConfig  # noqa: F401
