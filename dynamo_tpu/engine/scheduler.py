"""Continuous batching scheduler: admission, chunked prefill, decode batching.

Pure host-side logic (no jax imports) mirroring the semantics of the
reference's engine schedulers it delegates to, and of its own mocker
scheduler (ref: lib/llm/src/mocker/scheduler.rs:240 — admission watermark,
chunked prefill budget, preemption; vLLM-style recompute preemption):

- A sequence's lifecycle: waiting → running (prefill chunks → decode steps)
  → finished, with a ``swapped`` station between waiting and running:
  preempted victims whose KV was staged to host DRAM (preempt-to-swap) park
  there and re-enter ``running`` at their old progress once blocks free up —
  only when the host budget is exhausted (or a bundle is torn down) does a
  victim fall back to the classic release-and-recompute path.
- ``num_computed`` counts tokens whose KV is in the paged cache;
  ``remaining = len(tokens) - num_computed``; remaining==1 means the next
  step computes the last token's KV and samples (decode); remaining>1 means
  a prefill chunk (which also samples iff it reaches the end).
- Prefix-cache admission: full prompt blocks are matched against the
  BlockPool by chained sequence hash (same salted-xxh3 domain as the
  frontend/router — dynamo_tpu/tokens.py), skipping their recompute.
- KV events: as blocks fill they are registered + reported stored; pool
  eviction reports removed — feeding the router's radix index exactly like
  the reference's engines do (ref: kv_router/publisher.rs).
"""

from __future__ import annotations

import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from dynamo_tpu.engine.cache import BlockPool
from dynamo_tpu.engine.config import RAGGED_MAX_CHUNKS, EngineArgs
from dynamo_tpu.protocols import FinishReason, LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.qos import CLASS_RANK, DEFAULT_TENANT, normalize_priority
from dynamo_tpu.qos.fair import ClassQueues, QosBook
from dynamo_tpu.router.protocols import StoredBlock
from dynamo_tpu.tokens import KV_HASH_SEED, TokenBlockSequence

logger = logging.getLogger("dynamo.engine.scheduler")

#: starvation guard for the swapped queue (docs/qos.md): a swap-in
#: candidate whose block reservation fails this many consecutive passes is
#: re-parked behind its peers (dynamo_swap_in_blocked_total counts it) so a
#: large head-of-line sequence cannot block smaller resumable ones forever
SWAP_IN_SKIP_AFTER = 3


@dataclass
class SeqState:
    request_id: str
    req: PreprocessedRequest
    ctx: object  # runtime Context (has .cancelled)
    sink: object  # asyncio.Queue for outputs (owned by engine)
    tokens: list[int] = field(default_factory=list)  # prompt + generated
    prompt_len: int = 0
    hashes: TokenBlockSequence = None
    block_table: list[int] = field(default_factory=list)
    num_computed: int = 0  # tokens whose KV is resident
    num_registered_blocks: int = 0  # blocks already registered/evented
    num_cached_prompt: int = 0  # prefix-cache hit tokens (for metrics)
    generated: int = 0
    step_idx: int = 0  # sampling step counter (PRNG determinism)
    finished: Optional[str] = None
    preemptions: int = 0
    #: disagg: keep KV blocks alive past finish (owner gathers then releases)
    hold_blocks: bool = False
    #: speculative decoding: incrementally-built n-gram → end-position index
    #: over ``tokens`` (engine._draft_tokens) — avoids O(n) history scans
    #: per decode step
    ngram_pos: dict = field(default_factory=dict)
    ngram_indexed: int = 0
    #: sampling penalties: incrementally-folded token history (engine
    #: _sample.build_triples) — ``gen_counts`` counts GENERATED tokens
    #: (presence/frequency), ``seen_tokens`` is distinct prompt+generated
    #: (repetition), ``pen_indexed`` the fold watermark into ``tokens``
    gen_counts: dict = field(default_factory=dict)
    seen_tokens: set = field(default_factory=set)
    pen_indexed: int = 0
    #: guided decoding constraint cursor (llm/guided.GuidedState), attached
    #: by the engine when the request carries guided options
    guided_state: object = None
    #: disagg pipelining: called with (num_computed) after each prefill chunk
    #: commits — lets the owner ship finished blocks while later chunks run
    progress_cb: Optional[Callable] = None
    #: preempt-to-swap: the engine's host-side swap entry while this seq's
    #: KV lives off-device (None = not swapped)
    swap: object = None
    #: per-request KV-event batching: stored blocks accumulated across
    #: prefill chunks, flushed as ONE chained event when the prompt
    #: completes (or at finish/preemption) — docs/PERF_NOTES.md fleet_bench
    pending_stored: list = field(default_factory=list)
    pending_stored_ids: list = field(default_factory=list)
    pending_parent: object = None
    #: multi-tenant QoS (docs/qos.md): tenant id + priority class copied
    #: off the Context at add() time (wire fields; absent = defaults),
    #: plus the bookkeeping the fair queues / starvation guards key on
    tenant: str = DEFAULT_TENANT
    priority: str = "standard"
    qos_enqueue_t: float = 0.0    # when the seq (re-)entered waiting
    qos_arrival: Optional[int] = None  # global arrival stamp (ClassQueues)
    swap_in_attempts: int = 0     # consecutive failed swap-in reservations
    parked_t: float = 0.0         # when the seq entered the swapped queue

    @property
    def remaining(self) -> int:
        return len(self.tokens) - self.num_computed

    def sampling_tuple(self):
        s = self.req.sampling_options
        return (
            float(s.temperature if s.temperature is not None else 0.0),
            int(s.top_k if s.top_k else 0),
            float(s.top_p if s.top_p is not None else 1.0),
            s.seed,  # None = unseeded (seed=0 is a valid pinned seed)
        )


@dataclass
class PrefillWork:
    seq: SeqState
    start: int
    chunk: int  # number of tokens to compute this step
    sample: bool  # True when the chunk reaches the end of tokens


@dataclass
class StepPlan:
    #: prefill chunks batched into ONE jitted call (same-bucket rows);
    #: empty list = no prefill this step
    prefill: list[PrefillWork] = field(default_factory=list)
    decode: list[SeqState] = field(default_factory=list)

    @property
    def empty(self) -> bool:
        return not self.prefill and not self.decode


class Scheduler:
    """Plans one engine iteration; owns admission/preemption/bookkeeping."""

    def __init__(self, args: EngineArgs, pool: BlockPool,
                 on_stored: Optional[Callable] = None,
                 onboard_cb: Optional[Callable] = None,
                 swapper: Optional[object] = None,
                 token_budget: bool = True,
                 hot_cb: Optional[Callable] = None):
        self.args = args
        self.pool = pool
        #: ragged-step planning (docs/performance.md), the ONLY planning
        #: mode: the step is ONE packed launch, so plan() budgets TOKENS
        #: (prefill chunks + decode rows co-scheduled under
        #: max_num_batched_tokens). Chunk sizes are free (no prefill-bucket
        #: clamp), padding-cost row checks are moot (nothing pads to a
        #: bucket), and the QoS decode sit-out collapses to plain budget
        #: accounting: better-class chunks are admitted first (class
        #: order), and decode rows cost one token each — they never
        #: inflate a better-class prefill's padded step shape, so there is
        #: nothing to shed. (``token_budget`` is accepted for API
        #: compatibility and ignored — the bucketed planner is gone.)
        self.token_budget = True
        self.on_stored = on_stored  # fn(parent_hash, [StoredBlock], [block_id])
        #: fn(probe: TokenBlockSequence, start_block, end_block) -> [block_id]
        #: — KVBM onboard hook: device-misses found in host/disk tiers come
        #: back as freshly scattered device blocks extending the prefix hit
        self.onboard_cb = onboard_cb
        #: fn(probe, hit_blocks) — prefix-HIT popularity hook: the G4
        #: flow-up policy (engine._note_hot_prefix) counts repeat hits and
        #: pushes hot prefixes to the fleet-global object store
        self.hot_cb = hot_cb
        #: preempt-to-swap backend (the engine): swap_out(seq) -> bool,
        #: swap_status(seq) -> "ready"|"pending"|"failed", swap_in(seq) ->
        #: bool, swap_drop(seq). None = recompute preemption only.
        self.swapper = swapper
        #: multi-tenant QoS ledger (virtual token counters, per-tenant
        #: telemetry) + the per-class waiting queues it drains. With QoS
        #: scheduling off — or a single default tenant/class, i.e. every
        #: pre-QoS workload — the drain order is exact FIFO.
        self.qos = QosBook(args.qos)
        self.waiting: ClassQueues = ClassQueues(
            self.qos, fifo=not args.qos_scheduling)
        self.running: list[SeqState] = []
        #: swapped-out victims — between waiting and running; swap-in
        #: admission runs BEFORE _admit so a resumed sequence reclaims its
        #: old position instead of queueing behind fresh prompts. Drained
        #: best-class-first (aged sequences jump the order), FIFO within a
        #: class; plain FIFO when QoS scheduling is off.
        self.swapped: deque[SeqState] = deque()
        self._aborted: set = set()  # reaped at next plan() like cancellation
        self.prefix_hit_tokens = 0
        self.prefix_query_tokens = 0
        #: one KV stored event per REQUEST (prefill chunks accumulate on the
        #: seq and flush when the prompt completes) unless per-chunk
        #: publishing was explicitly requested
        self._batch_events = not args.kv_event_per_chunk
        # preemption telemetry (→ dynamo_preempt_{swap,recompute}_total)
        self.preempt_swap_total = 0
        self.preempt_recompute_total = 0
        self.swap_in_total = 0
        #: prompt+generated tokens thrown away by recompute preemptions —
        #: each will be re-prefilled (the waste swap-based preemption kills)
        self.recomputed_tokens_total = 0
        #: swap-in starvation guard fires (head-of-line candidate re-parked
        #: after SWAP_IN_SKIP_AFTER failed reservations) →
        #: dynamo_swap_in_blocked_total
        self.swap_in_blocked_total = 0
        #: flight-recorder signal (observability/flight.py): decode rows
        #: that were READY last plan but did not fit the step (row cap /
        #: token budget), i.e. a budget-starved decode — QoS sit-out sheds
        #: are deliberate policy and are NOT counted here
        self.last_starved_decode = 0
        #: the starved rows' request (Context) ids — the flight record's
        #: step↔request linkage, so attribution can charge the stall to
        #: the request that actually sat out (observability/attribution.py)
        self.last_starved_ids: list = []

    # -- api ----------------------------------------------------------------

    @staticmethod
    def _salt_for(req) -> int:
        # multimodal content salts the block hashes: identical placeholder
        # tokens with different images must never share KV identity
        digest = req.mm_digest() if hasattr(req, "mm_digest") else None
        return KV_HASH_SEED if digest is None else digest

    def _stamp_qos(self, seq: SeqState) -> None:
        """Copy tenant/priority off the runtime Context (wire fields; a
        pre-QoS peer sends neither → defaults) and register the sequence
        with the fairness ledger."""
        seq.tenant = str(getattr(seq.ctx, "tenant", None)
                         or DEFAULT_TENANT)
        seq.priority = normalize_priority(
            getattr(seq.ctx, "priority", None), warn=False)
        self.qos.enter(seq)

    def add(self, seq: SeqState) -> None:
        seq.tokens = list(seq.req.token_ids)
        seq.prompt_len = len(seq.tokens)
        # PRNG step = ABSOLUTE token position, not per-seq generation
        # count: a migrated stream re-enters as prompt ‖ emitted, and the
        # tail must draw the same (seed, step) keys the unbroken run would
        # have — position-anchored steps make seeded sampling stable
        # across migration, disagg attach and recompute preemption alike
        seq.step_idx = seq.prompt_len
        seq.hashes = TokenBlockSequence(block_size=self.args.block_size,
                                        salt_hash=self._salt_for(seq.req))
        self._stamp_qos(seq)
        seq.qos_enqueue_t = time.monotonic()
        self.waiting.append(seq)

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.running or self.swapped)

    def num_waiting(self) -> int:
        return len(self.waiting)

    def plan(self) -> StepPlan:
        """Admission + one prefill chunk + the decode batch."""
        self._reap_cancelled()
        self._swap_in_pass()
        self._admit()
        plan = StepPlan()

        budget = self.args.max_num_batched_tokens
        # row cap for BOTH batch lists: the engine pads B to a
        # decode_batch_bucket, so more rows than the largest bucket would
        # overflow the padded batch arrays
        max_b = min(self.args.max_num_seqs, self.args.decode_batch_buckets[-1])
        decode_seqs = [s for s in self.running if s.remaining == 1]
        if self.args.qos_scheduling:
            # class-ordered work within the step (docs/qos.md): interactive
            # rows claim batch budget / row slots / the prefill token
            # bucket first, so an interactive prefill chunk never pads up
            # to (or queues a step behind) a concurrent batch prompt.
            # Stable within a class — single-class workloads keep the
            # exact pre-QoS order.
            order = {id(s): i for i, s in enumerate(self.running)}
            by_class = lambda s: (CLASS_RANK.get(s.priority, 1),  # noqa: E731
                                  order[id(s)])
            decode_seqs.sort(key=by_class)

        # ensure each decode seq has a block for its last position; preempt on
        # allocation failure (victims chosen newest-first, vLLM-style).
        # _preempt_for may evict a seq we already planned, so membership in
        # self.running is re-checked before the plan is finalized.
        ready_decode = []
        for s in decode_seqs:
            if s not in self.running:
                continue  # preempted by an earlier iteration
            if self._ensure_blocks(s, s.num_computed + 1):
                ready_decode.append(s)
            else:
                if not self._preempt_for(s):
                    self._preempt(s)
        # packed step: decode rows spend the shared token budget (one
        # token each) and must also fit the packed-token bucket cap
        row_cap = min(max_b, budget)
        still_ready = [s for s in ready_decode if s in self.running]
        plan.decode = still_ready[:row_cap]
        self.last_starved_decode = len(still_ready) - len(plan.decode)
        self.last_starved_ids = [
            rid for s in still_ready[row_cap:]
            if (rid := getattr(s.ctx, "id", None))]
        budget -= len(plan.decode)

        if self.args.enable_chunked_prefill or not plan.decode:
            # BATCHED prefill: several sequences' chunks ride one jitted
            # call as rows of a [B, S_bucket] batch. Rows share one token
            # bucket (the first chunk picks it; larger chunks wait for the
            # next step) and the PADDED cost B·S_bucket is bounded by
            # max_num_batched_tokens — concurrent prompts no longer
            # serialize one-prefill-per-step.
            prefill_seqs = [s for s in self.running if s.remaining > 1]
            if self.args.qos_scheduling:
                prefill_seqs.sort(key=by_class)
            # ragged planning has no per-row padding, so chunks are
            # clamped only by the step's token budget.
            cap = self.args.max_num_batched_tokens
            for s in prefill_seqs:
                if s not in self.running:
                    continue  # preempted by an earlier iteration's victim pick
                chunk = min(s.remaining, max(0, budget), cap)
                if not self.args.enable_chunked_prefill and chunk < s.remaining:
                    if s.remaining > cap:
                        # can never fit in one unchunked step: fail it rather
                        # than wedge the prefill queue forever
                        self.finish(s, FinishReason.ERROR)
                        s.sink.put_nowait(LLMEngineOutput(
                            finish_reason=FinishReason.ERROR,
                            text="prompt exceeds max_num_batched_tokens "
                                 "and chunked prefill is disabled"))
                        s.sink.put_nowait(None)
                    continue  # a shorter seq may still fit this step
                # the ragged step's chunk grid sizes for at most
                # RAGGED_MAX_CHUNKS co-scheduled chunks (model.
                # ragged_grid_shape capacity proof); later chunks wait
                # a step — they were budget-starved anyway
                prefill_cap = min(max_b, RAGGED_MAX_CHUNKS)
                if chunk <= 0 or len(plan.prefill) >= prefill_cap:
                    break
                protected = plan.decode + [w.seq for w in plan.prefill]
                if not self._ensure_blocks(s, s.num_computed + chunk):
                    # not enough memory: preempt, but never a seq whose
                    # block table this step's jitted calls are about to
                    # index — else wait
                    if not self._preempt_for(s, exclude=protected):
                        break
                    if not self._ensure_blocks(s, s.num_computed + chunk):
                        break
                plan.prefill.append(PrefillWork(
                    seq=s, start=s.num_computed, chunk=chunk,
                    sample=(s.num_computed + chunk == len(s.tokens)),
                ))
                budget -= chunk
        # NOTE: the bucketed planner's QoS decode sit-out (shed worse-class
        # decode rows when that shrank the compiled batch bucket) is gone
        # with the bucketed step itself: the packed ragged launch has no
        # padded batch bucket to shrink, so shedding rows would delay their
        # tokens without speeding the step by a single flop.
        return plan

    # -- post-step bookkeeping ----------------------------------------------

    def commit_computed(self, seq: SeqState, new_num_computed: int,
                        charge: bool = True) -> None:
        """Advance num_computed; hash/register/event newly-filled blocks.

        KV stored events batch PER REQUEST by default: chunks of a long
        prompt accumulate on the sequence and publish as one chained event
        when the prompt completes (decode-filled blocks still publish as
        they register — they arrive one per block_size tokens). Per-chunk
        publishing measured 11% under the 70B fleet's stored-blocks/s
        requirement; per-request has 2.3× headroom (docs/PERF_NOTES.md).
        """
        old = seq.num_computed
        seq.num_computed = new_num_computed
        # served-token accounting (docs/qos.md): every token whose KV this
        # engine computed — prefill chunks, decode steps, and recompute
        # re-prefills alike — advances the tenant's virtual counter at its
        # class weight. Prefix-cache hits and disagg-attached prompt KV
        # (charge=False) charge nothing: no work done HERE, and the prefill
        # worker already charged its own ledger, so charging again would
        # double-count dynamo_tenant_served_tokens_total fleet-wide.
        if charge:
            self.qos.charge(seq.tenant, seq.priority, new_num_computed - old)
        seq.hashes.extend(seq.tokens[len(seq.hashes): new_num_computed])
        bs = self.args.block_size
        full = new_num_computed // bs
        stored: list[StoredBlock] = []
        stored_ids: list[int] = []
        parent = None
        for i in range(seq.num_registered_blocks, full):
            blk = seq.hashes.blocks[i]
            bid = seq.block_table[i]
            fresh = self.pool.register(bid, blk.sequence_hash, blk.block_hash,
                                       blk.parent_sequence_hash)
            if fresh:
                if not stored:
                    parent = blk.parent_sequence_hash
                stored.append(StoredBlock(block_hash=blk.sequence_hash,
                                          tokens_hash=blk.block_hash))
                stored_ids.append(bid)
        seq.num_registered_blocks = full
        if not self.on_stored:
            return
        if self._batch_events and new_num_computed < seq.prompt_len:
            # mid-prompt chunk: park the delta; a later chunk (or finish/
            # preempt) flushes the whole chain in one event
            if stored:
                if not seq.pending_stored:
                    seq.pending_parent = parent
                seq.pending_stored.extend(stored)
                seq.pending_stored_ids.extend(stored_ids)
            return
        if seq.pending_stored:
            # consecutive blocks of one sequence: earlier chunks' blocks
            # chain straight into this one's, under the FIRST chunk's
            # parent. This path must run even when THIS commit registered
            # no new full block (a prompt whose tail is a partial block):
            # prompt completion is the flush point either way.
            stored = seq.pending_stored + stored
            stored_ids = seq.pending_stored_ids + stored_ids
            parent = seq.pending_parent
            seq.pending_stored, seq.pending_stored_ids = [], []
            seq.pending_parent = None
        if stored:
            self.on_stored(parent, stored, stored_ids)

    def _flush_stored(self, seq: SeqState) -> None:
        """Publish any batched-but-unflushed stored blocks. Must run BEFORE
        the seq's blocks are released (finish/preempt): the offload hook
        pins the block ids synchronously."""
        if seq.pending_stored and self.on_stored:
            self.on_stored(seq.pending_parent, seq.pending_stored,
                           seq.pending_stored_ids)
        seq.pending_stored, seq.pending_stored_ids = [], []
        seq.pending_parent = None

    def append_token(self, seq: SeqState, token: int) -> None:
        seq.tokens.append(token)
        seq.generated += 1
        seq.step_idx += 1

    def check_finish(self, seq: SeqState, token: int) -> Optional[str]:
        sc = seq.req.stop_conditions
        if not sc.ignore_eos and token in (seq.req.eos_token_ids or []):
            if (sc.min_tokens or 0) < seq.generated:
                return FinishReason.EOS
        gs = seq.guided_state
        if gs is not None and (gs.done or gs.exhausted):
            # constraint completed (or hit a token-level dead end): stop
            # even without EOS ids / with ignore_eos — free-running past
            # the constraint would emit unconstrained tokens. min_tokens
            # delays only the DONE stop; an exhausted machine has every
            # next token masked, so it must stop regardless
            if gs.exhausted or (sc.min_tokens or 0) <= seq.generated:
                return FinishReason.STOP
        if sc.max_tokens is not None and seq.generated >= sc.max_tokens:
            return FinishReason.LENGTH
        if seq.num_computed + 1 >= self.args.max_model_len:
            return FinishReason.LENGTH
        return None

    def finish(self, seq: SeqState, reason: str) -> None:
        seq.finished = reason
        self.qos.leave(seq)
        if seq.guided_state is not None:
            # structured decoding: drop the seq's device-FSM arena
            # reference so idle constraint tables become evictable
            # (duck-typed — the host oracle has no release)
            rel = getattr(seq.guided_state, "release", None)
            if rel is not None:
                rel()
        self._flush_stored(seq)
        if seq in self.running:
            self.running.remove(seq)
        if seq.swap is not None and self.swapper is not None:
            self.swapper.swap_drop(seq)
        if not seq.hold_blocks:
            self.pool.release(seq.block_table)
            seq.block_table = []

    def release_held(self, seq: SeqState) -> None:
        """Free the blocks of a finished hold_blocks sequence."""
        self.pool.release(seq.block_table)
        seq.block_table = []

    def add_prefilled(self, seq: SeqState, block_table: list[int]) -> None:
        """Admit a sequence whose prompt KV was computed elsewhere (disagg:
        decode worker receives prefill's pages already scattered into
        ``block_table``). Registers/hashes the prompt blocks so prefix cache
        and KV events behave exactly as if prefill ran locally."""
        seq.tokens = list(seq.req.token_ids)
        self._stamp_qos(seq)
        seq.prompt_len = len(seq.tokens)
        seq.step_idx = seq.prompt_len  # position-anchored PRNG (see add())
        seq.hashes = TokenBlockSequence(block_size=self.args.block_size,
                                        salt_hash=self._salt_for(seq.req))
        seq.block_table = list(block_table)
        self.running.append(seq)
        # charge=False: the prompt's KV was computed (and QoS-charged) on
        # the prefill worker; this engine only attaches the pages
        self.commit_computed(seq, seq.prompt_len, charge=False)

    # -- internals -----------------------------------------------------------

    def abort(self, seq: SeqState) -> None:
        """Owner vanished (e.g. prefill_extract cancelled): guarantee the
        seq's blocks return to the pool no matter what state it is in."""
        if seq.finished is not None:
            if seq.block_table:
                self.release_held(seq)
            return
        seq.hold_blocks = False  # eventual finish() must release
        self._aborted.add(id(seq))

    def _reap_cancelled(self) -> None:
        def dead(s):
            return getattr(s.ctx, "cancelled", False) or id(s) in self._aborted

        def expired(s):
            # end-to-end deadline (runtime Context): enforced at PLAN time so
            # an expired sequence never spends another device step
            return getattr(s.ctx, "expired", False)

        for s in list(self.running):
            if dead(s):
                self._aborted.discard(id(s))
                self.finish(s, FinishReason.CANCELLED)
                s.sink.put_nowait(None)  # unblock the generate() consumer
            elif expired(s):
                self.finish(s, FinishReason.DEADLINE)
                s.sink.put_nowait(LLMEngineOutput(
                    finish_reason=FinishReason.DEADLINE))
        for s in list(self.waiting):
            if dead(s):
                self._aborted.discard(id(s))
                s.finished = FinishReason.CANCELLED
                self.waiting.remove(s)
                self.qos.leave(s)
                s.sink.put_nowait(None)
            elif expired(s):
                s.finished = FinishReason.DEADLINE
                self.waiting.remove(s)
                self.qos.leave(s)
                s.sink.put_nowait(LLMEngineOutput(
                    finish_reason=FinishReason.DEADLINE))
        for s in list(self.swapped):
            # cancel-safe teardown: a swapped seq holds NO device blocks,
            # only a host bundle + budget reservation — drop both
            if dead(s) or expired(s):
                self._aborted.discard(id(s))
                self.swapped.remove(s)
                self.qos.leave(s)
                if self.swapper is not None:
                    self.swapper.swap_drop(s)
                if dead(s):
                    s.finished = FinishReason.CANCELLED
                    s.sink.put_nowait(None)
                else:
                    s.finished = FinishReason.DEADLINE
                    s.sink.put_nowait(LLMEngineOutput(
                        finish_reason=FinishReason.DEADLINE))

    def _swap_in_candidate(self, exclude: frozenset = frozenset()) -> SeqState:
        """Next swapped sequence to resume: aged ones first (oldest parked,
        starvation guard), then best class, then FIFO by park time. Plain
        FIFO when QoS scheduling is off.

        ``exclude`` holds ids of candidates already re-parked THIS pass:
        without it the class-first order re-picks a sole best-class
        candidate immediately after its own skip-ahead (re-parking only
        moves it behind same-class peers), and worse-class sequences
        behind it are never even tried."""
        if not self.args.qos_scheduling:
            return self.swapped[0]
        pool = [s for s in self.swapped if id(s) not in exclude] \
            or list(self.swapped)
        now = time.monotonic()
        aging = self.qos.cfg.aging_s
        if aging > 0:
            aged = [s for s in pool if now - s.parked_t >= aging]
            if aged:
                return min(aged, key=lambda s: s.parked_t)
        return min(pool,
                   key=lambda s: (CLASS_RANK.get(s.priority, 1), s.parked_t))

    def _swap_in_fallback(self, seq: SeqState) -> None:
        """Swap-in impossible (torn bundle / failed copy): resolve the
        preemption by recompute. Counted as recompute even though the
        swap-out counted as swap — or dashboards read 100% swap success
        while recomputed tokens climb."""
        self.preempt_recompute_total += 1
        self.recomputed_tokens_total += seq.num_computed
        self._reset_for_recompute(seq)
        seq.qos_enqueue_t = time.monotonic()
        self.waiting.appendleft(seq)

    def _swap_in_pass(self) -> None:
        """Re-activate swapped-out sequences when capacity returns.

        Swap-in admission charges ``_ensure_blocks`` for the sequence's
        whole resident prefix BEFORE re-activation (plus one token of
        headroom so the imminent decode/prefill step cannot immediately
        re-preempt it), and runs before ``_admit`` so a resumed sequence
        takes priority over fresh prompts — it resumes at its old progress
        instead of re-prefilling behind the queue.

        Starvation guard (docs/qos.md): a head-of-line candidate whose
        block reservation keeps failing — e.g. a long sequence needing more
        blocks than ever free at once — is re-parked behind its peers after
        ``SWAP_IN_SKIP_AFTER`` failed passes (``dynamo_swap_in_blocked_total``
        counts each re-park) so smaller resumable sequences get their shot.
        """
        if self.swapper is None:
            return
        rotations = 0
        skipped: set = set()  # re-parked this pass: don't re-pick them
        while self.swapped and len(self.running) < self.args.max_num_seqs:
            if rotations > len(self.swapped):
                break  # full cycle without progress: wait for more memory
            seq = self._swap_in_candidate(frozenset(skipped))
            st = self.swapper.swap_status(seq)
            if st == "pending":
                break  # host copy still in flight; order preserved
            if st != "ready":
                # bundle torn down / copy failed: recompute fallback
                self.swapped.remove(seq)
                logger.warning("swap-in of %s unavailable (%s); falling "
                               "back to recompute", seq.request_id, st)
                self.swapper.swap_drop(seq)  # reclaim budget/accounting
                self._swap_in_fallback(seq)
                continue
            bs = self.args.block_size
            need = (seq.num_computed + bs) // bs  # ceil((computed+1)/bs)
            free_after = self.pool.num_free_blocks - need
            watermarked = (self.running and self.pool.num_free_blocks - need
                           < self.args.watermark * self.pool.num_blocks)
            if free_after < 0 or watermarked:
                # not enough room for THIS candidate. A smaller sequence
                # behind it may still fit: after SWAP_IN_SKIP_AFTER failed
                # passes the candidate is re-parked (skip-ahead) instead of
                # pinning the whole queue behind its reservation.
                seq.swap_in_attempts += 1
                if (len(self.swapped) > 1
                        and seq.swap_in_attempts >= SWAP_IN_SKIP_AFTER):
                    seq.swap_in_attempts = 0
                    seq.parked_t = time.monotonic()  # back of its class
                    self.swapped.remove(seq)  # and of the FIFO order
                    self.swapped.append(seq)
                    skipped.add(id(seq))  # let worse classes have a shot
                    self.swap_in_blocked_total += 1
                    rotations += 1
                    logger.info("swap-in of %s blocked (needs %d blocks, "
                                "%d free); skipping ahead", seq.request_id,
                                need, self.pool.num_free_blocks)
                    continue
                break  # wait, don't thrash
            self.swapped.remove(seq)
            if not self._ensure_blocks(seq, seq.num_computed + 1):
                seq.swap_in_attempts += 1
                self.swapped.appendleft(seq)
                break
            seq.swap_in_attempts = 0
            if not self.swapper.swap_in(seq):
                self.pool.release(seq.block_table)
                seq.block_table = []
                self._swap_in_fallback(seq)  # resolved by recompute
                continue
            self.swap_in_total += 1
            # old position: ahead of every later admission, and victim
            # selection (newest-first) reaches it last
            self.running.insert(0, seq)

    def _make_room_for(self, seq: SeqState) -> bool:
        """Admission-time priority preemption (docs/qos.md): evict one
        running sequence of a STRICTLY worse class — lowest class /
        highest debt / newest first, through the swap path when the host
        budget allows — so an arriving higher-priority request gets its
        slot and blocks now instead of queueing behind saturated batch
        work. Same-class running work is never churned. False = no
        eligible victim (the arrival waits like before)."""
        if not self.args.qos_scheduling:
            return False
        rank = CLASS_RANK.get(seq.priority, 1)
        for victim in self._victim_order(seq):
            if CLASS_RANK.get(victim.priority, 1) <= rank:
                continue
            self._preempt(victim)
            return True
        return False

    def _admit(self) -> None:
        bs = self.args.block_size
        now = time.monotonic()
        while self.waiting:
            # weighted-fair pick (docs/qos.md): the backlogged tenant with
            # the least virtual time goes first (aging escape hatch for
            # starving sequences; exact FIFO with QoS scheduling off or a
            # single default tenant/class)
            seq = self.waiting.pick(now)
            # slots full: a higher-priority arrival may claim one from a
            # worse-class victim; anything else waits. The freed capacity
            # goes to THIS seq, not a re-pick — a recompute-preempted
            # victim lands back in waiting with a lower virtual time than
            # the arrival that displaced it, and a re-pick would hand it
            # straight back its old slot and preempt it again, forever.
            # _make_room_for only ever evicts strictly-worse classes, so
            # each call shrinks running and the loop is bounded.
            while len(self.running) >= self.args.max_num_seqs:
                if not self._make_room_for(seq):
                    return
            # watermark: keep a fraction of blocks free (ref: mocker watermark)
            needed_first = max(1, min(len(seq.tokens), bs) // bs + 1)
            while (self.pool.num_free_blocks < needed_first
                   or (self.running and self.pool.num_free_blocks
                       < self.args.watermark * self.pool.num_blocks)):
                if not self._make_room_for(seq):
                    return
            self.waiting.remove(seq)
            self.qos.note_queue_wait(seq.tenant, seq.priority,
                                     max(0.0, now - seq.qos_enqueue_t))
            if seq.num_computed == 0 and not seq.block_table:
                self._prefix_match(seq)
            self.running.append(seq)

    def _prefix_match(self, seq: SeqState) -> None:
        self.prefix_query_tokens += seq.prompt_len
        if not self.args.enable_prefix_caching:
            return
        bs = self.args.block_size
        # match only full *prompt* blocks, and never the whole prompt — at
        # least one token must be computed to produce logits
        matchable = (seq.prompt_len - 1) // bs
        if matchable <= 0:
            return
        # the probe MUST use the same salt as registration: an unsalted
        # probe would let a multimodal request reuse KV computed for the
        # same tokens WITHOUT its image embeddings (and vice versa)
        probe = TokenBlockSequence.from_tokens(
            seq.tokens[: matchable * bs], bs, self._salt_for(seq.req))
        hit_blocks = self.pool.match_prefix(probe.sequence_hashes())
        if self.onboard_cb is not None and len(hit_blocks) < matchable:
            hit_blocks = hit_blocks + self.onboard_cb(
                probe, len(hit_blocks), matchable)
        if not hit_blocks:
            return
        n = len(hit_blocks)
        if self.hot_cb is not None:
            # popularity signal for the G4 prefix flow-up: this leading
            # run was just re-used (never fired on the cold first compute)
            self.hot_cb(probe, n)
        seq.block_table = list(hit_blocks)
        seq.num_computed = n * bs
        seq.num_cached_prompt = n * bs
        seq.num_registered_blocks = n
        seq.hashes.extend(seq.tokens[: n * bs])
        self.prefix_hit_tokens += n * bs

    def _ensure_blocks(self, seq: SeqState, target_tokens: int) -> bool:
        bs = self.args.block_size
        need = (target_tokens + bs - 1) // bs - len(seq.block_table)
        if need <= 0:
            return True
        got = self.pool.allocate(need)
        if got is None:
            return False
        seq.block_table.extend(got)
        return True

    def _preempt_for(self, needy: SeqState, exclude=()) -> bool:
        """Preempt another running seq to free memory. True if any.

        Victim order under QoS (docs/qos.md): lowest priority class first
        (batch before standard before interactive), then the tenant with
        the most accumulated service (highest virtual time — the "debt"
        that weighted fairness says should yield first), then newest. A
        victim of a BETTER class than the needy sequence is never taken —
        the needy one preempts itself instead (caller falls through to
        ``_preempt(needy)``), which is exactly how interactive KV survives
        batch pressure. With QoS scheduling off: newest-first, vLLM-style.

        ``exclude`` protects sequences already finalized into this step's
        decode batch: evicting one would free the very block table the
        imminent jitted call is about to index (the bench-on-TPU crash —
        a prefill chunk preempting a planned decode mid-step).
        """
        for victim in self._victim_order(needy):
            if victim is needy or any(victim is e for e in exclude):
                continue
            self._preempt(victim)
            return True
        return False

    def _victim_order(self, needy: SeqState) -> list[SeqState]:
        if not self.args.qos_scheduling:
            return list(reversed(self.running))
        needy_rank = CLASS_RANK.get(needy.priority, 1)
        idx = {id(s): i for i, s in enumerate(self.running)}
        candidates = [s for s in self.running
                      if CLASS_RANK.get(s.priority, 1) >= needy_rank]
        return sorted(
            candidates,
            key=lambda s: (CLASS_RANK.get(s.priority, 1),
                           self.qos.vt_of(s.tenant), idx[id(s)]),
            reverse=True)

    def _preempt(self, seq: SeqState) -> None:
        """Evict a victim to free KV blocks: swap its resident pages to the
        host tier when the swapper accepts (budget available), else the
        classic release-and-recompute. Either way the victim's device
        blocks return to the pool THIS plan — the swap gather is dispatched
        against the immutable current cache array before release."""
        self._flush_stored(seq)  # blocks are still resident: pinnable
        if (self.swapper is not None and seq.num_computed > 0
                and seq.block_table and self.swapper.swap_out(seq)):
            logger.info("preempting request %s (swap-out, %d tokens)",
                        seq.request_id, seq.num_computed)
            self.pool.release(seq.block_table)
            seq.block_table = []
            seq.preemptions += 1
            self.preempt_swap_total += 1
            self.qos.note_preempt(seq.tenant, seq.priority)
            if seq in self.running:
                self.running.remove(seq)
            seq.parked_t = time.monotonic()
            seq.swap_in_attempts = 0
            self.swapped.append(seq)
            return
        if seq.num_computed > 0:
            self.qos.note_preempt(seq.tenant, seq.priority)
            # a zero-progress victim (admitted, nothing computed) discards
            # no KV — requeueing it is free and counts as neither a swap
            # nor a recompute preemption
            logger.warning("preempting request %s (recompute)",
                           seq.request_id)
            self.preempt_recompute_total += 1
            self.recomputed_tokens_total += seq.num_computed
        self.pool.release(seq.block_table)
        seq.block_table = []
        self._reset_for_recompute(seq)
        seq.preemptions += 1
        if seq in self.running:
            self.running.remove(seq)
        seq.qos_enqueue_t = time.monotonic()
        self.waiting.appendleft(seq)

    def _reset_for_recompute(self, seq: SeqState) -> None:
        """Zero a sequence's computed-KV bookkeeping so admission re-runs
        its prefill from scratch (the recompute-preemption path)."""
        seq.num_computed = 0
        seq.num_registered_blocks = 0
        seq.num_cached_prompt = 0
        seq.hashes = TokenBlockSequence(block_size=self.args.block_size,
                                        salt_hash=self._salt_for(seq.req))
