"""The in-process operator pipeline: preprocess → detokenize → migrate → route.

Rebuild of the reference's canonical engine chain (ref: lib/llm/src/entrypoint/
input/common.rs:259-312): every model served over HTTP gets

    frontend → OpenAIPreprocessor → Backend(detokenizer) → Migration → client

where ``client`` issues the request to a worker instance (possibly KV-routed).
Operators are async-generator transformers over ``(request, Context)``; the
request flows "forward" through each operator, the response stream flows
"backward" being transformed at each hop.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import Any, AsyncIterator, Callable, Optional

from dynamo_tpu.protocols import (
    Annotated,
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
)
from dynamo_tpu.protocols.openai import (
    ParsedRequest,
    chat_chunk,
    completion_chunk,
    gen_request_id,
    usage_block,
)
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.tokenizer import TokenizerWrapper
from dynamo_tpu.runtime.context import (
    Context,
    DeadlineExceededError,
    StreamError,
)
from dynamo_tpu.runtime.control_plane import NoRespondersError

logger = logging.getLogger("dynamo.pipeline")

#: downstream engine: async generator fn of (request, ctx) -> LLMEngineOutput stream
EngineFn = Callable[[Any, Context], AsyncIterator[Any]]


def is_event(item: Any) -> bool:
    """True for Annotated out-of-band events (annotations, dry-route replies)
    that must pass through operators untransformed."""
    return isinstance(item, dict) and "event" in item and "token_ids" not in item


# ---------------------------------------------------------------------------
# Preprocessor
# ---------------------------------------------------------------------------


class OpenAIPreprocessor:
    """OpenAI request → PreprocessedRequest; engine stream → OpenAI chunks.

    ref: lib/llm/src/preprocessor.rs:158-280 (apply_template :279, tokenize :205).
    """

    def __init__(self, mdc: ModelDeploymentCard, tokenizer: TokenizerWrapper, downstream: EngineFn):
        self.mdc = mdc
        self.tokenizer = tokenizer
        self.downstream = downstream
        self._template_env = None

    def _openai_logprobs(self, out: LLMEngineOutput, chat: bool,
                         k: int) -> dict:
        """Engine top_logprobs → OpenAI logprobs objects (chat: content
        entries with alternatives; completions: tokens/token_logprobs/
        top_logprobs arrays). ``k`` is the REQUESTED alternatives count —
        0 emits selected-token logprobs with empty alternative lists.
        ref surface: perf/logprobs.rs consumes exactly these shapes."""
        tk = self.tokenizer
        logps = out.log_probs or [None] * len(out.token_ids)
        if chat:
            content = []
            for tid, lp, tops in zip(out.token_ids, logps, out.top_logprobs):
                content.append({
                    "token": tk.decode([tid]),
                    "logprob": lp,
                    "top_logprobs": [
                        {"token": tk.decode([int(t)]), "logprob": p}
                        for t, p in (tops or [])[:k]],
                })
            return {"content": content}
        return {
            "tokens": [tk.decode([tid]) for tid in out.token_ids],
            "token_logprobs": list(logps),
            "top_logprobs": [
                {tk.decode([int(t)]): p for t, p in (tops or [])[:k]}
                for tops in out.top_logprobs],
        }

    def _render_chat(self, req: ParsedRequest) -> str:
        import jinja2

        template_src = (
            self.mdc.chat_template
            or self.tokenizer.chat_template
        )
        if not template_src:
            # crude concatenation fallback
            return "\n".join(f"{m['role']}: {m.get('content', '')}" for m in req.messages) + "\nassistant:"
        if self._template_env is None:
            self._template_env = jinja2.Environment(keep_trailing_newline=True)
            self._template_env.globals["raise_exception"] = _jinja_raise
        template = self._template_env.from_string(template_src)
        return template.render(
            messages=req.messages,
            tools=req.tools,
            add_generation_prompt=True,
            bos_token=self.tokenizer.bos_token or "",
            eos_token=self.tokenizer.eos_token or "",
        )

    @staticmethod
    def _extract_mm(messages):
        """Replace image_url content parts with unique sentinels; returns
        (rewritten messages, [ref urls]) — the sentinels survive chat-template
        rendering so image positions can be located after tokenization."""
        refs: list[str] = []
        out = []
        for m in messages:
            c = m.get("content")
            if isinstance(c, list):
                parts = []
                for part in c:
                    if isinstance(part, dict) and part.get("type") == "image_url":
                        url = (part.get("image_url") or {}).get("url", "")
                        parts.append(f"\x00mm{len(refs)}\x00")
                        refs.append(url)
                    elif isinstance(part, dict) and "text" in part:
                        # strip NULs: user text must never be able to forge
                        # a sentinel and alias/crash image placement
                        parts.append(str(part["text"]).replace("\x00", ""))
                m = dict(m, content="".join(parts))
            elif isinstance(c, str) and "\x00" in c:
                # plain-string messages can forge sentinels too
                m = dict(m, content=c.replace("\x00", ""))
            out.append(m)
        return out, refs

    def _tokenize_mm(self, prompt: str, refs: list[str]):
        """Split the rendered prompt at sentinels, tokenize segments
        separately, and insert placeholder runs per image — segment-wise
        tokenization is the only scheme stable across tokenizers (a sentinel
        tokenized inline splits unpredictably)."""
        import re

        n_ph = self.mdc.mm_placeholder_tokens
        token_ids: list[int] = []
        mm_refs = []
        pieces = re.split("\x00mm(\\d+)\x00", prompt)
        # pieces = [text, idx, text, idx, ..., text]
        for i, piece in enumerate(pieces):
            if i % 2 == 0:
                # the FIRST segment is always encoded (even when empty) so
                # a prompt that begins with an image still gets its BOS
                if piece or i == 0:
                    token_ids.extend(self.tokenizer.encode(
                        piece, add_special_tokens=(i == 0)))
            else:
                mm_refs.append({"start": len(token_ids),
                                "ref": refs[int(piece)], "tokens": n_ph})
                token_ids.extend([0] * n_ph)  # placeholder run
        return token_ids, mm_refs

    def _apply_tool_choice(self, req: ParsedRequest
                           ) -> tuple[ParsedRequest, bool]:
        """Enforce ``tool_choice`` (docs/structured.md) — it is never
        silently ignored:

        * ``"none"``: tools are stripped BEFORE template rendering, so the
          model never sees the schemas and no tool parser runs.
        * ``"required"`` / named tool: the tool parameter schemas compile
          into a constraint grammar (structured/tools.py) in the model's
          tool-parser markup, attached as the request's guided constraint —
          the model cannot emit anything but a valid call. Unsupported
          parser markup or schema keywords raise (→ frontend 400) rather
          than free-decoding and hoping.

        Returns (request, enforced) — ``enforced`` selects a JSON tool
        parser for models with none configured, so constrained output
        still round-trips into ``tool_calls``.
        """
        tc = req.tool_choice
        if tc in (None, "auto"):
            return req, False
        import dataclasses as _dc

        if tc == "none":
            return _dc.replace(req, tools=None, tool_choice=None), False
        from dynamo_tpu.llm.guided import validate_guided
        from dynamo_tpu.structured.tools import tool_constraint

        parser = self.mdc.runtime_config.tool_call_parser
        pattern = tool_constraint(req.tools or [], tc, parser)
        validate_guided({"regex": pattern})  # clear 400, not a worker error
        sampling = _dc.replace(req.sampling, guided={"regex": pattern})
        return _dc.replace(req, sampling=sampling), True

    def preprocess(self, req: ParsedRequest) -> tuple[PreprocessedRequest, str]:
        mm_refs = None
        if req.messages is not None:
            messages, refs = self._extract_mm(req.messages)
            if refs:
                import dataclasses as _dc

                prompt = self._render_chat(_dc.replace(req, messages=messages))
                token_ids, mm_refs = self._tokenize_mm(prompt, refs)
            else:
                prompt = self._render_chat(req)
                token_ids = self.tokenizer.encode(prompt)
        else:
            p = req.prompt
            if isinstance(p, str):
                prompt = p
                token_ids = self.tokenizer.encode(p)
            elif isinstance(p, list) and all(isinstance(t, int) for t in p):
                prompt = ""
                token_ids = list(p)
            else:
                raise ValueError("unsupported prompt type (batch prompts not yet supported)")

        max_in = self.mdc.context_length
        if len(token_ids) >= max_in:
            raise ValueError(
                f"prompt length {len(token_ids)} exceeds model context length {max_in}"
            )
        stop = req.stop
        if stop.max_tokens is None:
            stop.max_tokens = max_in - len(token_ids)
        stop.max_tokens = min(stop.max_tokens, max_in - len(token_ids))
        stop.apply_ignore_eos()

        pre = PreprocessedRequest(
            model=req.model,
            token_ids=token_ids,
            stop_conditions=stop,
            sampling_options=req.sampling,
            output_options=req.output,
            eos_token_ids=list(self.mdc.eos_token_ids),
            mdc_sum=self.mdc.checksum(),
            annotations=req.annotations,
            backend_instance_id=req.backend_instance_id,
            router_config_override=req.router_config_override,
            mm_refs=mm_refs,
        )
        return pre, prompt

    async def generate(self, req: ParsedRequest, ctx: Context) -> AsyncIterator[dict]:
        """Yields Annotated-wire dicts whose ``data`` are OpenAI chunk objects."""
        from dynamo_tpu.observability import get_tracer

        req, tools_enforced = self._apply_tool_choice(req)
        is_chat = req.messages is not None
        with get_tracer().span("preprocess.tokenize", ctx,
                               service="frontend") as sp:
            pre, prompt = self.preprocess(req)
            sp.set(n_prompt_tokens=len(pre.token_ids), chat=is_chat)

        request_id = gen_request_id("chatcmpl" if is_chat else "cmpl")
        created = int(time.time())

        if "formatted_prompt" in req.annotations:
            yield Annotated(event="formatted_prompt", data=prompt, id=ctx.id).to_wire()
        if "token_ids" in req.annotations:
            yield Annotated(event="token_ids", data=pre.token_ids, id=ctx.id).to_wire()

        # output parsers from the model card (ref: lib/parsers — applied at
        # the frontend like the reference's parser registry)
        reasoning = None
        tool_parser_name = None
        if is_chat:
            from dynamo_tpu.parsers import get_reasoning_parser
            rc = self.mdc.runtime_config
            reasoning = get_reasoning_parser(rc.reasoning_parser)
            if rc.tool_call_parser and req.tools:
                tool_parser_name = rc.tool_call_parser
            elif tools_enforced and req.tools:
                # enforcement without a configured parser constrains to
                # bare JSON (structured/tools.py default markup) — parse it
                # with the JSON tool parser so the call still surfaces as
                # tool_calls instead of streaming as content
                tool_parser_name = "llama3_json"
            elif hasattr(reasoning, "route_tools_to_reasoning"):
                # tool-less request on a harmony model: no tool parser will
                # run, so the channel parser must NOT pass commentary
                # segments through raw (the <|...|> markup would stream
                # verbatim as content) — route them into reasoning instead,
                # markup stripped, and keep final-channel streaming live
                reasoning.route_tools_to_reasoning = True
        # with a tool parser active, content is buffered and parsed at stream
        # end (a partial tool call must never leak as content)
        tool_buf: Optional[list] = [] if tool_parser_name else None

        n_prompt = len(pre.token_ids)
        n_completion = 0
        first = True
        async for out in self.downstream(pre, ctx):
            if is_event(out):
                yield out  # already Annotated wire form
                continue
            if isinstance(out, dict):
                out = LLMEngineOutput.from_wire(out)
            if out.finish_reason == FinishReason.ERROR:
                yield Annotated.from_error(out.text or "engine error").to_wire()
                return
            n_completion += len(out.token_ids)
            finish = FinishReason.to_openai(out.finish_reason)
            text = out.text or ""
            lp = (self._openai_logprobs(out, is_chat, req.output.logprobs or 0)
                  if out.top_logprobs else None)
            if not is_chat:
                chunk = completion_chunk(
                    request_id, req.model, created, text=text,
                    finish_reason=finish, logprobs=lp
                )
                if out.finish_reason is not None:
                    # always attached: the frontend records token metrics
                    # from it (planner's ISL/OSL source) and strips it from
                    # the client stream unless stream_options asked
                    chunk["usage"] = usage_block(n_prompt, n_completion)
                yield Annotated(data=chunk, id=ctx.id).to_wire()
                continue

            r_delta = ""
            if reasoning is not None:
                r_delta, text = reasoning.feed(text)
                if out.finish_reason is not None:
                    r_tail, c_tail = reasoning.finalize()
                    r_delta += r_tail
                    text += c_tail
            if tool_buf is not None:
                tool_buf.append(text)
                text = ""
                if out.finish_reason is None and not r_delta:
                    continue  # content buffered; nothing to stream this step
            if out.finish_reason is not None and tool_buf is not None:
                from dynamo_tpu.parsers import parse_tool_calls
                normal, calls = parse_tool_calls(tool_parser_name, "".join(tool_buf))
                if calls:
                    finish = "tool_calls"
                    chunk = chat_chunk(
                        request_id, req.model, created,
                        role="assistant" if first else None,
                        content=normal or None,
                        tool_calls=[dict(tc.to_openai(), index=i)
                                    for i, tc in enumerate(calls)],
                        reasoning_content=r_delta or None,
                        finish_reason=finish, logprobs=lp,
                    )
                else:
                    chunk = chat_chunk(
                        request_id, req.model, created,
                        role="assistant" if first else None,
                        content=normal,
                        reasoning_content=r_delta or None,
                        finish_reason=finish, logprobs=lp,
                    )
            else:
                emit_content = text if (text or not finish) else None
                chunk = chat_chunk(
                    request_id, req.model, created,
                    role="assistant" if first else None,
                    content=emit_content,
                    reasoning_content=r_delta or None,
                    finish_reason=finish, logprobs=lp,
                )
            first = False
            if out.finish_reason is not None:
                chunk["usage"] = usage_block(n_prompt, n_completion)
            yield Annotated(data=chunk, id=ctx.id).to_wire()


def _jinja_raise(msg):
    raise ValueError(msg)


# ---------------------------------------------------------------------------
# Backend (incremental detokenizer with hidden-stop-sequence jail)
# ---------------------------------------------------------------------------


class StopSequenceJail:
    """Holds back text that might be the start of a stop string.

    ref: lib/llm/src/backend.rs:47-533 — the returned output must not contain
    the stop strings, so any tail that is a prefix of a stop sequence is
    "jailed" until disambiguated.
    """

    def __init__(self, stops: list[str]):
        self.stops = [s for s in (stops or []) if s]
        self._buf = ""

    def feed(self, text: str) -> tuple[str, bool]:
        """Returns (emit_text, hit_stop)."""
        if not self.stops:
            return text, False
        self._buf += text
        for s in self.stops:
            idx = self._buf.find(s)
            if idx != -1:
                emit = self._buf[:idx]
                self._buf = ""
                return emit, True
        # longest suffix of buf that is a prefix of any stop
        hold = 0
        for s in self.stops:
            for k in range(min(len(s) - 1, len(self._buf)), 0, -1):
                if self._buf.endswith(s[:k]):
                    hold = max(hold, k)
                    break
        if hold:
            emit, self._buf = self._buf[:-hold], self._buf[-hold:]
        else:
            emit, self._buf = self._buf, ""
        return emit, False

    def flush(self) -> str:
        out, self._buf = self._buf, ""
        return out


class Backend:
    """Detokenizing operator: token_ids → text deltas, finish-reason mapping."""

    def __init__(self, tokenizer: TokenizerWrapper, downstream: EngineFn):
        self.tokenizer = tokenizer
        self.downstream = downstream

    async def generate(self, req: PreprocessedRequest, ctx: Context) -> AsyncIterator[LLMEngineOutput]:
        decoder = self.tokenizer.decode_stream(
            skip_special_tokens=req.output_options.skip_special_tokens
        )
        jail = StopSequenceJail(req.stop_conditions.stop or [])
        hidden_stops = set(req.stop_conditions.stop_token_ids_hidden or [])
        eos_ids = set(req.eos_token_ids)
        ignore_eos = bool(req.stop_conditions.ignore_eos)
        min_tokens = req.stop_conditions.min_tokens or 0
        emitted = 0

        async for out in self.downstream(req, ctx):
            if is_event(out):
                yield out
                continue
            if isinstance(out, dict):
                out = LLMEngineOutput.from_wire(out)
            if out.finish_reason == FinishReason.ERROR:
                yield out
                return
            text_parts = []
            stop_hit = None
            for tid in out.token_ids:
                emitted += 1
                if not ignore_eos and emitted > min_tokens and (tid in hidden_stops or tid in eos_ids):
                    stop_hit = FinishReason.STOP if tid in hidden_stops else FinishReason.EOS
                    break
                piece = decoder.step(tid)
                if piece:
                    emit, hit = jail.feed(piece)
                    if emit:
                        text_parts.append(emit)
                    if hit:
                        stop_hit = FinishReason.STOP
                        break
            text = "".join(text_parts)
            if stop_hit is not None:
                yield LLMEngineOutput(
                    token_ids=out.token_ids, text=text,
                    cum_log_probs=out.cum_log_probs,
                    log_probs=out.log_probs,
                    top_logprobs=out.top_logprobs,
                    finish_reason=stop_hit, index=out.index,
                )
                return
            finish = out.finish_reason
            if finish is not None and finish not in (FinishReason.ERROR,):
                # engine finished (length/eos/cancelled): flush nothing from the
                # jail — jailed text is by definition a stop-string prefix, but
                # with no stop hit it is legitimate tail text.
                tail = jail.flush()
                if tail:
                    text += tail
            yield LLMEngineOutput(
                token_ids=out.token_ids,
                text=text,
                cum_log_probs=out.cum_log_probs,
                log_probs=out.log_probs,
                top_logprobs=out.top_logprobs,
                finish_reason=finish,
                index=out.index,
                kv_transfer_params=out.kv_transfer_params,
            )
            if finish is not None:
                return


# ---------------------------------------------------------------------------
# Migration (stream-level fault tolerance)
# ---------------------------------------------------------------------------

#: process-wide migration outcome totals, exported by the frontend as
#: ``dynamo_stream_migrations_total{outcome}`` and joined into the fleet
#: scorecard (observability/scorecard.py) — this is how a drive's
#: kill→migrate→zero-loss path becomes visible without parsing logs.
#: outcomes: resend (each re-issued leg), completed (stream finished after
#: ≥1 migration), exhausted (retryable break with no budget left)
_MIGRATION_STATS: dict[str, int] = {}


def _note_migration(outcome: str) -> None:
    _MIGRATION_STATS[outcome] = _MIGRATION_STATS.get(outcome, 0) + 1


def migration_stats() -> dict[str, int]:
    """Snapshot of the process-wide migration outcome counters."""
    return dict(_MIGRATION_STATS)


class Migration:
    """Replays a broken stream on a new worker with accumulated tokens.

    ref: lib/llm/src/migration.rs:26-716 + docs/architecture/request_migration.md:
    on a mid-stream transport error the request is re-issued with
    ``token_ids + tokens_emitted_so_far`` so the new worker continues where
    the dead one stopped; bounded by the MDC's ``migration_limit``.

    Retry policy (docs/robustness.md): only RETRYABLE stream errors are
    re-sent — typed terminal failures (overload shedding, deadline expiry)
    re-raise immediately so the budget is never burned against a fleet that
    will reject again. Re-sends back off exponentially with full jitter
    (thundering-herd protection when a worker death breaks many streams at
    once), capped by the request's remaining deadline.
    """

    #: full-jitter backoff: sleep ~U(0, min(CAP, BASE * 2**attempt))
    BACKOFF_BASE_S = 0.025
    BACKOFF_CAP_S = 1.0

    def __init__(self, downstream: EngineFn, migration_limit: int = 3):
        self.downstream = downstream
        self.migration_limit = migration_limit

    def _backoff_s(self, attempt: int, ctx: Context) -> Optional[float]:
        """Jittered delay before re-send ``attempt`` (1-based), clamped to
        the request's remaining deadline budget. None = budget exhausted."""
        delay = random.uniform(
            0.0, min(self.BACKOFF_CAP_S, self.BACKOFF_BASE_S * (2 ** attempt)))
        remaining = ctx.remaining_s()
        if remaining is None:
            return delay
        if remaining <= 0:
            return None
        return min(delay, remaining)

    async def generate(self, req: PreprocessedRequest, ctx: Context) -> AsyncIterator[LLMEngineOutput]:
        accumulated: list[int] = []
        budget = self.migration_limit if req.backend_instance_id is None else 0
        attempt = 0
        current = req
        # flight identity of the worker currently serving (first frame of
        # each leg carries it): a re-send's restore hint names it as the
        # PREDECESSOR so latency attribution stitches both legs' step
        # intervals instead of writing leg 1 off as unattributed
        # (docs/observability.md "Attribution")
        last_flight: Optional[dict] = None
        while True:
            try:
                async for out in self.downstream(current, ctx):
                    if is_event(out):
                        yield out
                        continue
                    if isinstance(out, dict):
                        out = LLMEngineOutput.from_wire(out)
                    if out.flight is not None:
                        last_flight = out.flight
                    accumulated.extend(out.token_ids)
                    if out.finish_reason is not None:
                        # account BEFORE the final yield: downstream
                        # operators return as soon as they see the finish
                        # frame (detokenizer jail-break, aggregators),
                        # which closes this generator at the yield — code
                        # after it never runs and `completed` flatlines at
                        # zero no matter how many migrations succeeded
                        if attempt:
                            _note_migration("completed")
                        yield out
                        return
                    yield out
                if attempt:
                    _note_migration("completed")
                return
            except DeadlineExceededError:
                if accumulated:
                    # the stream already carried tokens: end it cleanly with
                    # the deadline reason instead of a mid-stream exception
                    yield LLMEngineOutput(finish_reason=FinishReason.DEADLINE)
                    return
                raise
            except (StreamError, NoRespondersError) as e:
                # NoRespondersError = fleet blackout (every instance dead at
                # once, e.g. correlated kills): transient under operator
                # supervision, so it burns the migration budget like a
                # retryable transport loss — the backoff window is exactly
                # the operator's restart window. On exhaustion it re-raises
                # and keeps its type (frontend maps it to 503).
                retryable = (e.retryable if isinstance(e, StreamError)
                             else True)
                if not retryable or budget <= 0 or ctx.cancelled:
                    if retryable and budget <= 0 and not ctx.cancelled:
                        _note_migration("exhausted")
                    raise
                if ctx.expired:
                    if accumulated:
                        yield LLMEngineOutput(
                            finish_reason=FinishReason.DEADLINE)
                        return
                    raise DeadlineExceededError(
                        "deadline expired while migrating") from e
                budget -= 1
                attempt += 1
                _note_migration("resend")
                remaining = None
                if req.stop_conditions.max_tokens is not None:
                    # against the ORIGINAL budget: current's max_tokens was
                    # already reduced by earlier migrations while
                    # ``accumulated`` is cumulative — subtracting from it
                    # again truncated twice-migrated streams early
                    remaining = req.stop_conditions.max_tokens - len(accumulated)
                    if remaining <= 0:
                        yield LLMEngineOutput(finish_reason=FinishReason.LENGTH)
                        return
                delay = self._backoff_s(attempt, ctx)
                if delay is None:  # raced to expiry since the check above
                    if accumulated:
                        yield LLMEngineOutput(
                            finish_reason=FinishReason.DEADLINE)
                        return
                    raise DeadlineExceededError(
                        "deadline expired while migrating") from e
                logger.warning(
                    "migrating request %s after %d tokens (%s); %d retries "
                    "left, backoff %.0f ms",
                    ctx.id, len(accumulated), e, budget, delay * 1000,
                )
                new_stop = _clone_stop(current.stop_conditions, remaining)
                current = PreprocessedRequest(
                    model=current.model,
                    token_ids=list(req.token_ids) + accumulated,
                    stop_conditions=new_stop,
                    sampling_options=current.sampling_options,
                    output_options=current.output_options,
                    eos_token_ids=current.eos_token_ids,
                    mdc_sum=current.mdc_sum,
                    annotations=current.annotations,
                    router_config_override=current.router_config_override,
                    # multimodal payload MUST ride the re-send: without it
                    # the new worker decodes placeholder tokens as plain
                    # text (silently wrong output), and the mm salt in the
                    # block hashes would no longer match the fleet's KV
                    mm_embeds=current.mm_embeds,
                    mm_refs=current.mm_refs,
                    # stateful migration (docs/robustness.md): mark the
                    # re-send so the router can attach a KV-restore plan
                    # and the receiving worker can rebuild the recoverable
                    # prefix from surviving peers instead of re-prefilling.
                    # prev_* carries the broken leg's flight identity +
                    # step seq for the attribution stitch; t_break (epoch)
                    # bounds that leg's wall-clock interval.
                    restore={"emitted": len(accumulated),
                             "attempt": attempt,
                             **({"prev_worker": last_flight["worker"],
                                 "prev_name": last_flight.get("recorder"),
                                 "prev_seq": last_flight.get("seq"),
                                 "t_break": time.time()}
                                if last_flight else {})},
                )
                last_flight = None  # the next leg announces itself afresh
                await asyncio.sleep(delay)


def _clone_stop(sc, max_tokens: Optional[int]):
    from dataclasses import replace

    return replace(sc, max_tokens=max_tokens if max_tokens is not None else sc.max_tokens)


# ---------------------------------------------------------------------------
# Composition helpers
# ---------------------------------------------------------------------------


def build_pipeline(
    mdc: ModelDeploymentCard,
    tokenizer: TokenizerWrapper,
    engine: EngineFn,
) -> "OpenAIPreprocessor":
    """frontend-facing engine = Preprocessor(Backend(Migration(engine)))."""
    migration = Migration(engine, migration_limit=mdc.migration_limit)
    backend = Backend(tokenizer, migration.generate)
    return OpenAIPreprocessor(mdc, tokenizer, backend.generate)


async def aggregate_chat_stream(stream: AsyncIterator[dict]) -> dict:
    """Fold a chunk stream into a non-streaming chat completion response."""
    content: dict[int, list[str]] = {}
    reasoning: dict[int, list[str]] = {}
    tool_calls: dict[int, list[dict]] = {}
    logprobs: dict[int, list[dict]] = {}
    finish: dict[int, Optional[str]] = {}
    base: Optional[dict] = None
    usage = None
    async for wire in stream:
        ann = Annotated.from_wire(wire)
        if ann.is_error():
            raise RuntimeError("; ".join(ann.comment or ["stream error"]))
        if ann.event is not None:
            continue
        chunk = ann.data
        base = base or chunk
        usage = chunk.get("usage") or usage
        for ch in chunk.get("choices", []):
            idx = ch.get("index", 0)
            delta = ch.get("delta") or {}
            if delta.get("content"):
                content.setdefault(idx, []).append(delta["content"])
            if delta.get("reasoning_content"):
                reasoning.setdefault(idx, []).append(delta["reasoning_content"])
            if delta.get("tool_calls"):
                tool_calls.setdefault(idx, []).extend(delta["tool_calls"])
            if (ch.get("logprobs") or {}).get("content"):
                logprobs.setdefault(idx, []).extend(ch["logprobs"]["content"])
            if ch.get("finish_reason"):
                finish[idx] = ch["finish_reason"]
    if base is None:
        raise RuntimeError("empty response stream")
    choices = []
    for idx in sorted(set(content) | set(finish) | set(tool_calls)
                      | set(reasoning) | {0}):
        msg: dict = {"role": "assistant",
                     "content": "".join(content.get(idx, []))}
        if idx in reasoning:
            msg["reasoning_content"] = "".join(reasoning[idx])
        if idx in tool_calls:
            msg["tool_calls"] = [
                {k: v for k, v in tc.items() if k != "index"}
                for tc in tool_calls[idx]
            ]
            msg["content"] = msg["content"] or None
        choice = {
            "index": idx,
            "message": msg,
            "finish_reason": finish.get(idx),
        }
        if idx in logprobs:
            choice["logprobs"] = {"content": logprobs[idx]}
        choices.append(choice)
    return {
        "id": base["id"],
        "object": "chat.completion",
        "created": base["created"],
        "model": base["model"],
        "choices": choices,
        "usage": usage or usage_block(0, 0),
    }


async def aggregate_completion_stream(stream: AsyncIterator[dict]) -> dict:
    texts: dict[int, list[str]] = {}
    finish: dict[int, Optional[str]] = {}
    logprobs: dict[int, dict[str, list]] = {}
    base = None
    usage = None
    async for wire in stream:
        ann = Annotated.from_wire(wire)
        if ann.is_error():
            raise RuntimeError("; ".join(ann.comment or ["stream error"]))
        if ann.event is not None:
            continue
        chunk = ann.data
        base = base or chunk
        usage = chunk.get("usage") or usage
        for ch in chunk.get("choices", []):
            idx = ch.get("index", 0)
            if ch.get("text"):
                texts.setdefault(idx, []).append(ch["text"])
            if ch.get("logprobs"):  # concat per-chunk token arrays
                agg = logprobs.setdefault(idx, {
                    "tokens": [], "token_logprobs": [], "top_logprobs": []})
                for k in agg:
                    agg[k].extend(ch["logprobs"].get(k) or [])
            if ch.get("finish_reason"):
                finish[idx] = ch["finish_reason"]
    if base is None:
        raise RuntimeError("empty response stream")
    choices = [
        {
            "index": idx,
            "text": "".join(texts.get(idx, [])),
            "finish_reason": finish.get(idx),
            "logprobs": logprobs.get(idx),
        }
        for idx in sorted(set(texts) | set(finish) | {0})
    ]
    return {
        "id": base["id"],
        "object": "text_completion",
        "created": base["created"],
        "model": base["model"],
        "choices": choices,
        "usage": usage or usage_block(0, 0),
    }
