"""ops/: Pallas paged-attention kernel (interpret mode) vs XLA reference;
block gather/scatter round-trips."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.ops.block_copy import gather_blocks, scatter_blocks
from dynamo_tpu.ops.paged_attention import (
    paged_attention_decode, paged_attention_decode_xla,
)


def make_case(key, B=4, H=8, KV=4, hd=32, bs=8, num_blocks=64, W=6,
              dtype=jnp.float32):
    ks = jax.random.split(key, 4)
    q = jax.random.normal(ks[0], (B, H, hd), dtype)
    k_cache = jax.random.normal(ks[1], (num_blocks * bs, KV, hd), dtype)
    v_cache = jax.random.normal(ks[2], (num_blocks * bs, KV, hd), dtype)
    rng = np.random.default_rng(0)
    bt = np.zeros((B, W), np.int32)
    kv_lens = np.zeros((B,), np.int32)
    for i in range(B):
        n = int(rng.integers(1, W * bs))
        kv_lens[i] = n
        used = (n + bs - 1) // bs
        blocks = rng.choice(np.arange(1, num_blocks), size=used, replace=False)
        bt[i, :used] = blocks
    return q, k_cache, v_cache, jnp.asarray(bt), jnp.asarray(kv_lens)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_paged_attention_kernel_matches_xla(dtype):
    q, kc, vc, bt, kl = make_case(jax.random.key(0), dtype=dtype)
    want = paged_attention_decode_xla(q, kc, vc, bt, kl, block_size=8)
    got = paged_attention_decode(q, kc, vc, bt, kl, block_size=8, interpret=True)
    tol = 2e-5 if dtype == jnp.float32 else 2e-2
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        atol=tol, rtol=tol)


def test_paged_attention_kernel_one_page():
    q, kc, vc, bt, kl = make_case(jax.random.key(1), W=1, bs=16)
    kl = jnp.minimum(kl, 16)
    want = paged_attention_decode_xla(q, kc, vc, bt, kl, block_size=16)
    got = paged_attention_decode(q, kc, vc, bt, kl, block_size=16, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_gather_scatter_roundtrip():
    L, nb, bs, KV, hd = 2, 16, 4, 2, 8
    cache = jnp.arange(L * nb * bs * KV * hd, dtype=jnp.float32).reshape(
        L, nb * bs, KV, hd)
    ids = [3, 7, 1]
    bundle = np.asarray(gather_blocks(cache, ids, block_size=bs))
    assert bundle.shape == (L, 4, bs, KV, hd)  # pow2-padded (last id repeats)
    np.testing.assert_array_equal(bundle[:, 2], bundle[:, 3])
    bundle = bundle[:, : len(ids)]  # exact-n view, like the transfer path
    # write the bundle into different slots of an empty cache
    dst = jnp.zeros_like(cache)
    new_ids = [0, 2, 5]
    dst = scatter_blocks(dst, new_ids, bundle, block_size=bs)
    out = np.asarray(gather_blocks(dst, new_ids, block_size=bs))[:, : len(ids)]
    np.testing.assert_array_equal(out, bundle)
