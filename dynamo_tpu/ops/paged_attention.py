"""Pallas TPU paged-attention decode kernel.

One query token per sequence attends over its paged KV cache (the serving
hot loop). The XLA fallback in engine/model.py materializes the gathered
K/V [B, W·bs, KV, hd] through HBM; this kernel instead streams pages
HBM→VMEM with double-buffered async DMA and folds them into an online
softmax, so K/V traffic is read exactly once and never re-materialized.

Contract matches engine/model._paged_attention for S=1:
  q            [B, H, hd]
  k/v cache    [num_slots, KV, hd]   (flat paged layout, slot = block·bs+off)
  block_tables [B, W] int32          (0 = reserved null block)
  kv_lens      [B] int32             (valid kv length per sequence)
  → out        [B, H, hd]

TPU mapping: Mosaic requires DMA slices tile-aligned in the trailing dims
(lane = 128), which a [bs, KV, hd≤64] page view violates. So the kernel
works in the flattened [slots, KV·hd] view (KV·hd is a lane multiple for
real GQA models: 8·64=512): pages DMA as [bs, KV·hd]; scores come from one
MXU matmul of a block-expanded query Q̃ [H, KV·hd] (head h carries its q
only in its own KV segment, zeros elsewhere, so contraction over KV·hd
reduces to the correct per-group dot); PV accumulates in the [H, KV·hd]
domain and the correct segment per head is gathered outside the kernel.
The redundant-segment FLOPs are noise — decode attention is DMA-bound.

Falls back to the XLA path when shapes can't align (KV·hd % 128 ≠ 0).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

_NEG = -1e30
_LANE = 128


def _hbm_space(pltpu):
    """``pltpu.HBM`` where the jax version has it; ``ANY`` (compiler keeps
    un-blocked operands off VMEM) on versions that predate the alias."""
    return getattr(pltpu, "HBM", pltpu.ANY)


def _decode_kernel(block_tables_ref, kv_lens_ref, window_ref,
                   sbase_ref,  # scalar pf; sbase = scale-table slot base
                   qexp_ref,  # [1, H, KVhd] VMEM
                   sink_ref,  # [1, H, 1] VMEM (zeros when has_sink=False)
                   kcache_ref, vcache_ref,  # [slots, KVhd] HBM
                   *rest,  # [ksc_ref, vsc_ref (HBM [slots, KV] | VMEM),]
                           # out_ref, kbuf, vbuf, [ksbuf, vsbuf,] dma_sem
                   bs: int, has_sink: bool, quant: bool,
                   vmem_scales: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if quant and vmem_scales:
        # scales ride as ordinary VMEM operands (constant block → fetched
        # once for the whole grid): 2 DMAs/page, same as the bf16 path.
        # The r4 chip measurement showed the 4-DMA variant at 1557 tok/s vs
        # 4528 bf16 — the two tiny (bs·KV·4 B) scale copies pay full DMA
        # grant latency each, tripling effective page-fetch cost.
        ksc_ref, vsc_ref, out_ref, kbuf, vbuf, dma_sem = rest
        ksbuf = vsbuf = None
    elif quant:
        (ksc_ref, vsc_ref, out_ref, kbuf, vbuf,
         ksbuf, vsbuf, dma_sem) = rest
    else:
        out_ref, kbuf, vbuf, dma_sem = rest
        ksc_ref = vsc_ref = ksbuf = vsbuf = None

    b = pl.program_id(0)
    kv_len = kv_lens_ref[b]
    num_pages = (kv_len + bs - 1) // bs
    # sliding window (gpt-oss/mistral): pages entirely outside the window
    # are never fetched — a 128-token window reads 1-2 pages regardless of
    # context length. window<=0 means full attention.
    win = window_ref[0]
    first_key = jnp.where(win > 0, jnp.maximum(kv_len - win, 0), 0)
    start_page = first_key // bs
    H = qexp_ref.shape[1]
    KVhd = qexp_ref.shape[2]

    D = kbuf.shape[0]  # pipeline depth: D page fetches always in flight

    def start_dma(w):
        blk = block_tables_ref[b, w]
        slot = w % D
        pltpu.make_async_copy(
            kcache_ref.at[pl.ds(blk * bs, bs)], kbuf.at[slot],
            dma_sem.at[slot, 0]).start()
        pltpu.make_async_copy(
            vcache_ref.at[pl.ds(blk * bs, bs)], vbuf.at[slot],
            dma_sem.at[slot, 1]).start()
        if quant and not vmem_scales:
            # per-(slot, head) scales ride their own small DMAs; offsets
            # rebase onto the scale table (callers may pass ONE layer's
            # slice of a stacked cache — see scale_slot_base)
            soff = blk * bs - sbase_ref[0]
            pltpu.make_async_copy(
                ksc_ref.at[pl.ds(soff, bs)], ksbuf.at[slot],
                dma_sem.at[slot, 2]).start()
            pltpu.make_async_copy(
                vsc_ref.at[pl.ds(soff, bs)], vsbuf.at[slot],
                dma_sem.at[slot, 3]).start()

    def wait_dma(w):
        slot = w % D
        pltpu.make_async_copy(kbuf.at[slot], kbuf.at[slot],
                              dma_sem.at[slot, 0]).wait()
        pltpu.make_async_copy(vbuf.at[slot], vbuf.at[slot],
                              dma_sem.at[slot, 1]).wait()
        if quant and not vmem_scales:
            pltpu.make_async_copy(ksbuf.at[slot], ksbuf.at[slot],
                                  dma_sem.at[slot, 2]).wait()
            pltpu.make_async_copy(vsbuf.at[slot], vsbuf.at[slot],
                                  dma_sem.at[slot, 3]).wait()

    # D-deep rotating pipeline — scattered pages are independent, so keeping
    # D fetches in flight hides per-DMA grant latency (a 2-deep double
    # buffer serializes W·B small copies on that latency).
    prefill_n = jnp.minimum(num_pages, start_page + D)
    jax.lax.fori_loop(start_page, prefill_n,
                      lambda w, c: (start_dma(w), c)[1], 0)

    qexp = qexp_ref[0].astype(jnp.float32)  # [H, KVhd], block-expanded

    if quant:
        # static head→segment one-hot [H, KV]: head h's scale per key t is
        # seg_oh @ spage.T — one tiny MXU matmul instead of lane-expanding
        # scales to the [bs, KVhd] domain
        KV = ksc_ref.shape[0] if vmem_scales else ksbuf.shape[2]
        G = H // KV
        rows = jax.lax.broadcasted_iota(jnp.int32, (H, KV), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (H, KV), 1)
        seg_oh = (cols == rows // G).astype(jnp.float32)

    def body(w, carry):
        m, l, acc = carry  # [H,1] f32, [H,1] f32, [H,KVhd] f32
        wait_dma(w)
        kpage = kbuf[w % D].astype(jnp.float32)  # [bs, KVhd]
        vpage = vbuf[w % D].astype(jnp.float32)
        if quant and vmem_scales:
            # resident layout is TRANSPOSED [KV, padded_slots] (slots on the
            # lane dim — a [slots, KV] block would tile-pad KV→128, 16-128×
            # the useful bytes; ADVICE r4)
            blk = block_tables_ref[b, w]
            soff = blk * bs - sbase_ref[0]  # rebase onto the scale slice
            kscpage = ksc_ref[:, pl.ds(soff, bs)]  # [KV, bs] VMEM slice
            vscpage = vsc_ref[:, pl.ds(soff, bs)]
            sc_dims = (((1,), (0,)), ((), ()))  # seg_oh[H,KV] @ [KV,bs]
        elif quant:
            kscpage = ksbuf[w % D]  # [bs, KV]
            vscpage = vsbuf[w % D]
            sc_dims = (((1,), (1,)), ((), ()))

        # scores: contraction over KVhd == per-group q·k (q̃ is segment-masked)
        s = jax.lax.dot_general(
            qexp, kpage, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [H, bs]
        if quant:
            # dequant scores in the [H, bs] domain: each head contracts only
            # its own segment, so its raw score scales by that segment's
            # per-key k-scale
            ksc = jax.lax.dot_general(
                seg_oh, kscpage, sc_dims,
                preferred_element_type=jnp.float32)  # [H, bs]
            s = s * ksc

        key_pos = w * bs + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
        s = jnp.where((key_pos < kv_len) & (key_pos >= first_key), s, _NEG)

        chunk_max = jnp.max(s, axis=1, keepdims=True)
        new_m = jnp.maximum(m, chunk_max)
        corr = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m)  # [H, bs]
        new_l = l * corr + jnp.sum(p, axis=1, keepdims=True)
        pv_p = p
        if quant:
            # fold per-key v-scales into p (head h's own segment scaling;
            # other segments become garbage the caller discards anyway)
            vsc = jax.lax.dot_general(
                seg_oh, vscpage, sc_dims,
                preferred_element_type=jnp.float32)  # [H, bs]
            pv_p = p * vsc
        pv = jax.lax.dot_general(
            pv_p, vpage, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [H, KVhd]

        # refill this slot for page w+D — issued after the loads above, so
        # the in-order instruction stream can't overwrite data still in use
        @pl.when(w + D < num_pages)
        def _():
            start_dma(w + D)

        return new_m, new_l, acc * corr + pv

    if has_sink:
        # gpt-oss attention sink: an extra softmax slot with zero value
        # contribution — seed the online softmax with it (m=sink, l=1)
        m0 = sink_ref[0].astype(jnp.float32)  # [H, 1]
        l0 = jnp.ones((H, 1), jnp.float32)
    else:
        m0 = jnp.full((H, 1), _NEG, jnp.float32)
        l0 = jnp.zeros((H, 1), jnp.float32)
    acc0 = jnp.zeros((H, KVhd), jnp.float32)
    m, l, acc = jax.lax.fori_loop(start_page, num_pages, body,
                                  (m0, l0, acc0))

    out_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(out_ref.dtype)


def pallas_supported(num_kv_heads: int, head_dim: int) -> bool:
    return (num_kv_heads * head_dim) % _LANE == 0


def paged_attention_decode(q, k_cache, v_cache, block_tables, kv_lens, *,
                           block_size: int, interpret: bool = False,
                           window=None, sinks=None,
                           k_scales=None, v_scales=None,
                           scale_slot_base=None):
    """Decode-step paged attention. See module docstring for the contract.

    ``window``: sliding-window size as a (possibly traced per-layer) scalar
    — 0/None = full attention; pages outside the window are never fetched.
    ``sinks``: optional per-head attention-sink logits [H] (gpt-oss),
    seeded into the online softmax with zero value contribution.
    ``k_scales``/``v_scales`` [slots, KV] f32 (int8 caches): pages are int8
    and dequantize IN the kernel — HBM page traffic halves vs bf16, the
    decode bandwidth win the KV-capacity role of the reference's G1 tier
    implies (lib/llm/src/block_manager/).
    ``scale_slot_base`` (traced scalar, default 0): slot offset of the
    scale tables relative to the page cache — callers with a LAYER-STACKED
    flat cache pass one layer's scale slice plus ``lidx·slots`` so the
    VMEM-resident scale budget is per-layer, not ×L (serving-scale caches
    would otherwise always fall back to the slow 4-DMA path).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, hd = q.shape
    slots, KV, _ = k_cache.shape
    G = H // KV
    KVhd = KV * hd
    bs = block_size
    quant = k_scales is not None
    if not pallas_supported(KV, hd):
        return paged_attention_decode_xla(
            q, k_cache, v_cache, block_tables, kv_lens, block_size=bs,
            window=window, sinks=sinks, k_scales=k_scales,
            v_scales=v_scales, scale_slot_base=scale_slot_base)
    interpret = interpret or jax.default_backend() != "tpu"
    has_sink = sinks is not None
    win_arr = jnp.asarray([0 if window is None else window],
                          jnp.int32).reshape(1)
    sbase_arr = jnp.asarray([0 if scale_slot_base is None
                             else scale_slot_base], jnp.int32).reshape(1)
    sink_in = (jnp.zeros((1, H, 1), q.dtype) if not has_sink
               else sinks.reshape(1, H, 1).astype(q.dtype))

    # block-expand q: head h's vector sits in its own KV segment, zeros else
    seg = jnp.arange(H) // G  # [H]
    onehot = jax.nn.one_hot(seg, KV, dtype=q.dtype)  # [H, KV]
    qexp = jnp.einsum("bhd,hk->bhkd", q, onehot).reshape(B, H, KVhd)
    qexp = qexp * jnp.asarray(1.0 / np.sqrt(hd), q.dtype)  # fold in the scale

    W = block_tables.shape[1]
    D = min(W, 16)  # pipeline depth (VMEM budget: 2·D·bs·KVhd·dtype bytes)
    # int8 scale placement: resident in VMEM when both arrays fit the
    # budget (one fetch for the whole grid, 2 DMAs/page like bf16) — the
    # 4-DMA variant measured 2.9x slower on-chip (tiny scale copies pay
    # full grant latency). Budget overridable for experiments.
    vmem_scales = False
    if quant:
        # honest VMEM footprint of the lane-packed TRANSPOSED [KV, slots]
        # layout: sublane dim pads KV→8, lane dim pads slots→128. (The r4
        # [slots, KV] layout tile-padded its lane dim KV→128 — 16-128× the
        # bytes the old 2·slots·KV·4 check counted, so configs passed the
        # check yet overflowed VMEM at Mosaic compile time; ADVICE r4.)
        # Sized from the SCALE table, not the page cache: layer-stacked
        # callers pass one layer's slice (scale_slot_base), so the gate
        # and the packed operand are per-layer — an L·slots cache must
        # not fail the gate at L× the real residency.
        sc_slots = k_scales.shape[0]
        padded_slots = -(-sc_slots // _LANE) * _LANE
        scale_bytes = 2 * (-(-KV // 8) * 8) * padded_slots * 4
        budget = int(os.environ.get("DYN_KV_SCALE_VMEM_BYTES", 32 << 20))
        vmem_scales = scale_bytes <= budget
    kernel = functools.partial(_decode_kernel, bs=bs, has_sink=has_sink,
                               quant=quant, vmem_scales=vmem_scales)
    in_specs = [
        pl.BlockSpec((1, H, KVhd), lambda b, *_: (b, 0, 0)),
        pl.BlockSpec((1, H, 1), lambda b, *_: (0, 0, 0)),
        pl.BlockSpec(memory_space=_hbm_space(pltpu)),
        pl.BlockSpec(memory_space=_hbm_space(pltpu)),
    ]
    scratch = [
        pltpu.VMEM((D, bs, KVhd), k_cache.dtype),  # D pages in flight
        pltpu.VMEM((D, bs, KVhd), v_cache.dtype),
    ]
    operands = [k_cache.reshape(slots, KVhd), v_cache.reshape(slots, KVhd)]
    if quant:
        if vmem_scales:
            # constant block index → Pallas fetches the arrays once and
            # keeps them resident across the whole (B,) grid. Transposed so
            # slots ride the (cheap) lane dim — see the budget note above.
            def lane_pack_t(s):
                s = s.astype(jnp.float32).T  # [KV, sc_slots]
                return jnp.pad(s, ((0, 0), (0, padded_slots - sc_slots)))

            in_specs += [
                pl.BlockSpec((KV, padded_slots), lambda b, *_: (0, 0)),
                pl.BlockSpec((KV, padded_slots), lambda b, *_: (0, 0))]
            operands += [lane_pack_t(k_scales), lane_pack_t(v_scales)]
        else:
            in_specs += [pl.BlockSpec(memory_space=_hbm_space(pltpu)),
                         pl.BlockSpec(memory_space=_hbm_space(pltpu))]
            scratch += [pltpu.VMEM((D, bs, KV), jnp.float32),
                        pltpu.VMEM((D, bs, KV), jnp.float32)]
            operands += [k_scales.astype(jnp.float32),
                         v_scales.astype(jnp.float32)]
    scratch.append(
        pltpu.SemaphoreType.DMA((D, 4 if quant and not vmem_scales else 2)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, H, KVhd), lambda b, *_: (b, 0, 0)),
        scratch_shapes=scratch,
    )
    out_full = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, KVhd), q.dtype),
        interpret=interpret,
    )(block_tables, kv_lens, win_arr, sbase_arr, qexp, sink_in, *operands)

    # pick each head's own KV segment back out
    out_full = out_full.reshape(B, H, KV, hd)
    return jnp.take_along_axis(
        out_full, seg[None, :, None, None], axis=2).reshape(B, H, hd)


def paged_attention_decode_xla(q, k_cache, v_cache, block_tables, kv_lens, *,
                               block_size: int, window=None, sinks=None,
                               k_scales=None, v_scales=None,
                               scale_slot_base=None):
    """Reference/fallback path (same math, gather through XLA) — honors the
    same window/sink/int8 contract as the kernel, so a shape-based fallback
    can never silently change attention semantics."""
    B, H, hd = q.shape
    KV = k_cache.shape[1]
    G = H // KV
    W = block_tables.shape[1]
    T = W * block_size

    slot_idx = (block_tables[:, :, None] * block_size
                + jnp.arange(block_size)[None, None, :]).reshape(B, T)
    k = k_cache[slot_idx]  # [B, T, KV, hd]
    v = v_cache[slot_idx]
    if k_scales is not None:  # int8 pages: dequant fused into the gather
        sidx = slot_idx - (0 if scale_slot_base is None else scale_slot_base)
        k = k.astype(jnp.float32) * k_scales[sidx][..., None]
        v = v.astype(jnp.float32) * v_scales[sidx][..., None]
    qg = q.reshape(B, KV, G, hd).astype(jnp.float32)
    s = jnp.einsum("bkgd,btkd->bkgt", qg, k.astype(jnp.float32)) / np.sqrt(hd)
    key_pos = jnp.arange(T)
    mask = key_pos[None] < kv_lens[:, None]  # [B, T]
    if window is not None:
        win = jnp.asarray(window)
        mask = mask & ((win <= 0) | (key_pos[None] >= kv_lens[:, None] - win))
    s = jnp.where(mask[:, None, None], s, _NEG)
    if sinks is not None:  # combined softmax, sink slot contributes no value
        sk = sinks.astype(jnp.float32).reshape(KV, G)[None, :, :, None]
        m = jnp.maximum(s.max(-1), sk[..., 0])[..., None]
        e = jnp.exp(s - m)
        p = e / (e.sum(-1, keepdims=True) + jnp.exp(sk - m))
    else:
        p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgt,btkd->bkgd", p, v.astype(jnp.float32))
    return o.reshape(B, H, hd).astype(q.dtype)


# ---------------------------------------------------------------- MLA decode

def _mla_decode_kernel(block_tables_ref, kv_lens_ref,
                       sbase_ref,  # scalar prefetch; scale-table slot base
                       qe_ref,  # [1, H, R] VMEM (scale folded in)
                       qr_ref,  # [1, H, PR] VMEM
                       ccache_ref, rcache_ref,  # [slots, R] / [slots, PR] HBM
                       *rest,  # [csc_ref, rsc_ref (VMEM [slots, 1]),]
                               # out_ref, cbuf, rbuf, dma_sem
                       bs: int, quant: bool = False):
    """MLA is simpler than GQA here: every head attends over the SAME single
    latent page, so no block-expansion trick is needed — scores are
    q_eff·c + q_rot·rope (both lane-aligned MXU matmuls) and the VALUE is
    the latent itself; W_UV absorption happens outside.

    int8 pages (``quant``): the per-slot scales are ONE f32 per key,
    lane-packed [rows, 128] and VMEM-resident (no scale DMAs — the GQA
    lesson); callers gate on mla_int8_kernel_supported (VMEM budget +
    bs | 128) and fall back to the XLA gather path past it. Score parts
    dequant separately (c and rope carry different scales); the value
    dequant folds into p."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if quant:
        csc_ref, rsc_ref, out_ref, cbuf, rbuf, dma_sem = rest
    else:
        out_ref, cbuf, rbuf, dma_sem = rest
        csc_ref = rsc_ref = None

    b = pl.program_id(0)
    kv_len = kv_lens_ref[b]
    num_pages = (kv_len + bs - 1) // bs
    H, R = qe_ref.shape[1], qe_ref.shape[2]
    D = cbuf.shape[0]

    def start_dma(w):
        blk = block_tables_ref[b, w]
        slot = w % D
        pltpu.make_async_copy(
            ccache_ref.at[pl.ds(blk * bs, bs)], cbuf.at[slot],
            dma_sem.at[slot, 0]).start()
        pltpu.make_async_copy(
            rcache_ref.at[pl.ds(blk * bs, bs)], rbuf.at[slot],
            dma_sem.at[slot, 1]).start()

    def wait_dma(w):
        slot = w % D
        pltpu.make_async_copy(cbuf.at[slot], cbuf.at[slot],
                              dma_sem.at[slot, 0]).wait()
        pltpu.make_async_copy(rbuf.at[slot], rbuf.at[slot],
                              dma_sem.at[slot, 1]).wait()

    prefill_n = jnp.minimum(num_pages, D)
    jax.lax.fori_loop(0, prefill_n, lambda w, c: (start_dma(w), c)[1], 0)

    qe = qe_ref[0].astype(jnp.float32)  # [H, R]
    qr = qr_ref[0].astype(jnp.float32)  # [H, PR]

    def body(w, carry):
        m, l, acc = carry
        wait_dma(w)
        cpage = cbuf[w % D].astype(jnp.float32)  # [bs, R]
        rpage = rbuf[w % D].astype(jnp.float32)  # [bs, PR]
        if quant:
            blk = block_tables_ref[b, w]
            # scales are LANE-PACKED [rows, 128] (a [slots, 1] block would
            # tile-pad the lane dim 1→128, inflating VMEM 128×); a page's
            # bs scales sit inside one row because bs divides 128. The
            # offset rebases onto the (possibly layer-sliced) scale table.
            off = blk * bs - sbase_ref[0]
            csc = csc_ref[off // _LANE, pl.ds(off % _LANE, bs)].reshape(1, bs)
            rsc = rsc_ref[off // _LANE, pl.ds(off % _LANE, bs)].reshape(1, bs)

        sc = jax.lax.dot_general(
            qe, cpage, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [H, bs]
        sr = jax.lax.dot_general(
            qr, rpage, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if quant:
            # the two score parts carry DIFFERENT quant scales — dequant
            # each before summing
            s = sc * csc + sr * rsc
        else:
            s = sc + sr

        key_pos = w * bs + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
        s = jnp.where(key_pos < kv_len, s, _NEG)  # MLA: full attention

        chunk_max = jnp.max(s, axis=1, keepdims=True)
        new_m = jnp.maximum(m, chunk_max)
        corr = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m)
        new_l = l * corr + jnp.sum(p, axis=1, keepdims=True)
        # value IS the latent; its dequant folds into p (per-key scale)
        pv = jax.lax.dot_general(
            p * csc if quant else p, cpage, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [H, R]

        @pl.when(w + D < num_pages)
        def _():
            start_dma(w + D)

        return new_m, new_l, acc * corr + pv

    m0 = jnp.full((H, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((H, 1), jnp.float32)
    acc0 = jnp.zeros((H, R), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_pages, body, (m0, l0, acc0))
    out_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(out_ref.dtype)


def mla_pallas_supported(kv_lora_rank: int, rope_cache_dim: int) -> bool:
    return kv_lora_rank % _LANE == 0 and rope_cache_dim % _LANE == 0


def mla_int8_kernel_supported(block_size: int, flat_slots: int) -> bool:
    """Whether the int8 latent kernel can take this cache: a page's scales
    must sit in one lane row (bs | 128) and both lane-packed scale arrays
    must fit the VMEM budget (callers fall back to the XLA gather path
    otherwise)."""
    if _LANE % block_size:
        return False
    padded = -(-flat_slots // _LANE) * _LANE
    budget = int(os.environ.get("DYN_KV_SCALE_VMEM_BYTES", 32 << 20))
    return 2 * padded * 4 <= budget


def mla_paged_decode(q_eff, q_rot, latent_cache, rope_cache, block_tables,
                     kv_lens, *, block_size: int, scale: float,
                     interpret: bool = False,
                     c_scales=None, r_scales=None,
                     scale_slot_base=None):
    """MLA decode over the paged latent cache.

    q_eff [B,H,R] (queries absorbed through W_UK), q_rot [B,H,PR] (post-rope
    part, zero-padded to the cache's lane-aligned PR), latent_cache
    [slots,R], rope_cache [slots,PR] → attention output IN LATENT SPACE
    [B,H,R] (caller expands through W_UV). ``scale`` is the softmax scale
    (incl. YaRN mscale² — engine/model.mla_softmax_scale), folded into the
    queries here.

    ``c_scales``/``r_scales`` [slots] f32 (int8 caches): pages are int8 and
    dequantize in the kernel; scales ride lane-packed in VMEM (no scale
    DMAs). Callers must check :func:`mla_int8_kernel_supported` first.
    ``scale_slot_base``: slot offset of the scale tables relative to the
    page cache (layer-stacked callers pass one layer's slice + its base,
    keeping VMEM residency per-layer — same contract as
    paged_attention_decode).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, R = q_eff.shape
    PR = q_rot.shape[-1]
    bs = block_size
    quant = c_scales is not None
    interpret = interpret or jax.default_backend() != "tpu"

    qe = (q_eff.astype(jnp.float32) * scale).astype(q_eff.dtype)
    qr = (q_rot.astype(jnp.float32) * scale).astype(q_rot.dtype)
    sbase_arr = jnp.asarray([0 if scale_slot_base is None
                             else scale_slot_base], jnp.int32).reshape(1)

    W = block_tables.shape[1]
    D = min(W, 8)  # VMEM: D·bs·(R+PR)·dtype bytes in flight
    slots = (c_scales.shape[0] if quant else latent_cache.shape[0])
    kernel = functools.partial(_mla_decode_kernel, bs=bs, quant=quant)
    in_specs = [
        pl.BlockSpec((1, H, R), lambda b, *_: (b, 0, 0)),
        pl.BlockSpec((1, H, PR), lambda b, *_: (b, 0, 0)),
        pl.BlockSpec(memory_space=_hbm_space(pltpu)),
        pl.BlockSpec(memory_space=_hbm_space(pltpu)),
    ]
    operands = [latent_cache, rope_cache]
    if quant:
        # constant block index → fetched once, resident for the whole grid.
        # LANE-PACKED [rows, 128] so VMEM holds slots×4 bytes, not ×512
        # (a [slots, 1] block would pad its lane dim 1→128); callers gate
        # on mla_int8_kernel_supported for the budget + bs|128 invariants
        padded = -(-slots // _LANE) * _LANE
        rows = padded // _LANE

        def lane_pack(s):
            s = s.astype(jnp.float32)
            return jnp.pad(s, (0, padded - slots)).reshape(rows, _LANE)

        in_specs += [pl.BlockSpec((rows, _LANE), lambda b, *_: (0, 0)),
                     pl.BlockSpec((rows, _LANE), lambda b, *_: (0, 0))]
        operands += [lane_pack(c_scales), lane_pack(r_scales)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, H, R), lambda b, *_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((D, bs, R), latent_cache.dtype),
            pltpu.VMEM((D, bs, PR), rope_cache.dtype),
            pltpu.SemaphoreType.DMA((D, 2)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, R), q_eff.dtype),
        interpret=interpret,
    )(block_tables, kv_lens, sbase_arr, qe, qr, *operands)
