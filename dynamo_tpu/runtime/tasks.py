"""Hierarchical task tracker: structured concurrency for the runtime.

Behavior contract of the reference's tracker (ref: lib/runtime/src/utils/
tasks/tracker.rs:1-6565, tasks/critical.rs) rebuilt on asyncio:

- A tree of trackers: cancelling or joining a parent covers every child.
- ``spawn`` registers a task with an :class:`OnErrorPolicy` deciding what
  an unhandled exception does: log-and-continue, cancel this tracker's
  scope, or trip a process-wide shutdown callback (critical tasks).
- ``join(graceful_timeout)`` waits for inflight work, then cancels
  stragglers — the graceful-shutdown drain.
- An optional semaphore bounds concurrent tasks per tracker (the
  reference's pluggable scheduler policy).
"""

from __future__ import annotations

import asyncio
import enum
import logging
from typing import Callable, Optional

logger = logging.getLogger("dynamo.tasks")


class OnErrorPolicy(enum.Enum):
    #: log the exception, keep everything else running (default)
    CONTINUE = "continue"
    #: cancel every task in this tracker (and its children)
    CANCEL_SCOPE = "cancel_scope"
    #: invoke the root's shutdown callback — the process must exit
    SHUTDOWN = "shutdown"


class TaskTracker:
    def __init__(self, name: str = "root",
                 max_concurrency: Optional[int] = None,
                 on_shutdown: Optional[Callable] = None,
                 parent: Optional["TaskTracker"] = None):
        self.name = name
        self._tasks: set[asyncio.Task] = set()
        self._children: list[TaskTracker] = []
        self._parent = parent
        self._sem = (asyncio.Semaphore(max_concurrency)
                     if max_concurrency else None)
        self._on_shutdown = on_shutdown
        self._closed = False
        self.errors = 0

    # -- hierarchy -----------------------------------------------------------

    def child(self, name: str,
              max_concurrency: Optional[int] = None) -> "TaskTracker":
        c = TaskTracker(f"{self.name}/{name}", max_concurrency, parent=self)
        # a child born after join() inherits the drained state — otherwise
        # its spawns would escape the structured-concurrency guarantee
        c._closed = self._closed
        self._children.append(c)
        return c

    def _root_shutdown(self):
        node: TaskTracker = self
        while node._parent is not None and node._on_shutdown is None:
            node = node._parent
        if node._on_shutdown is not None:
            node._on_shutdown()
        else:
            logger.error("tracker %s: SHUTDOWN policy fired but no shutdown "
                         "callback is installed at the root", self.name)

    # -- spawning ------------------------------------------------------------

    def spawn(self, coro, name: str = "task",
              on_error: OnErrorPolicy = OnErrorPolicy.CONTINUE) -> asyncio.Task:
        """Track a coroutine; its failure is handled per ``on_error``."""
        if self._closed:
            coro.close()
            raise RuntimeError(f"tracker {self.name} is closed")

        async def run():
            try:
                if self._sem is not None:
                    async with self._sem:
                        return await coro
                return await coro
            except asyncio.CancelledError:
                coro.close()  # cancelled before first await: don't leak it
                raise

        task = asyncio.get_running_loop().create_task(run(), name=name)
        self._tasks.add(task)

        def done(t: asyncio.Task):
            self._tasks.discard(t)
            if t.cancelled():
                return
            exc = t.exception()
            if exc is None:
                return
            self.errors += 1
            logger.error("tracker %s: task %s failed: %r",
                         self.name, name, exc)
            if on_error is OnErrorPolicy.CANCEL_SCOPE:
                self.cancel_all()
            elif on_error is OnErrorPolicy.SHUTDOWN:
                self._root_shutdown()

        task.add_done_callback(done)
        return task

    # -- lifecycle -----------------------------------------------------------

    @property
    def inflight(self) -> int:
        return (len([t for t in self._tasks if not t.done()])
                + sum(c.inflight for c in self._children))

    def cancel_all(self) -> None:
        """Cancel every task in this subtree."""
        for t in list(self._tasks):
            t.cancel()
        for c in self._children:
            c.cancel_all()

    def _close_tree(self) -> None:
        self._closed = True
        for c in self._children:
            c._close_tree()

    def _tree_tasks(self) -> list:
        out = list(self._tasks)
        for c in self._children:
            out.extend(c._tree_tasks())
        return [t for t in out if not t.done()]

    async def join(self, graceful_timeout: Optional[float] = None) -> None:
        """Drain: wait for inflight work (up to ``graceful_timeout``), then
        cancel the stragglers. Covers the WHOLE subtree (children,
        grandchildren, …). The subtree refuses new spawns afterwards."""
        self._close_tree()
        pending = self._tree_tasks()
        if pending and graceful_timeout != 0:
            done, pending_set = await asyncio.wait(
                pending, timeout=graceful_timeout)
            pending = list(pending_set)
        if pending:
            logger.warning("tracker %s: cancelling %d straggler task(s)",
                           self.name, len(pending))
            for t in pending:
                t.cancel()
            await asyncio.gather(*pending, return_exceptions=True)
