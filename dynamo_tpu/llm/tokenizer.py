"""Tokenizer wrapper with incremental (streaming) detokenization.

Rebuild of the reference's tokenizer layer (ref: lib/llm/src/tokenizers.rs:1-564,
backend.rs DecodeStream usage): wraps an HF ``tokenizers.Tokenizer`` and exposes
encode/decode plus a stateful per-request decode stream.

``make_test_tokenizer`` builds a small deterministic WordLevel tokenizer in
memory so the whole pipeline (and CI) runs without model downloads.
"""

from __future__ import annotations

import logging
import re

import json
import os
from typing import Optional

from tokenizers import Tokenizer
from tokenizers.decoders import DecodeStream


class TokenizerWrapper:
    def __init__(self, tokenizer: Tokenizer, chat_template: Optional[str] = None,
                 bos_token: Optional[str] = None, eos_token: Optional[str] = None):
        self._tk = tokenizer
        self.chat_template = chat_template
        self.bos_token = bos_token
        self.eos_token = eos_token
        self.eos_token_id: Optional[int] = (
            tokenizer.token_to_id(eos_token) if eos_token else None
        )
        self.bos_token_id: Optional[int] = (
            tokenizer.token_to_id(bos_token) if bos_token else None
        )

    @property
    def vocab_size(self) -> int:
        return self._tk.get_vocab_size()

    def encode(self, text: str, add_special_tokens: bool = True) -> list[int]:
        return self._tk.encode(text, add_special_tokens=add_special_tokens).ids

    def decode(self, ids: list[int], skip_special_tokens: bool = True) -> str:
        return self._tk.decode(ids, skip_special_tokens=skip_special_tokens)

    def token_to_id(self, token: str) -> Optional[int]:
        return self._tk.token_to_id(token)

    def decode_stream(self, skip_special_tokens: bool = True) -> "IncrementalDecoder":
        return IncrementalDecoder(self._tk, skip_special_tokens)

    def guided_vocab(self) -> list[str]:
        """id → the EXACT text each token contributes mid-sequence — the
        alphabet for guided decoding's token-level DFA
        (llm/guided.TokenMachine). Per-id decode() is wrong for that:
        detokenizers are not pointwise (decode(t1+t2) != decode(t1)+
        decode(t2)) — byte-level BPEs spell a leading space as "Ġ" and
        SentencePiece as "▁", both of which single-token decode strips.
        The token STRINGS carry the truth, so they are transformed
        directly (Ġ/byte-map inversion, ▁→space). Specials map to "" and
        are thus never constraint-eligible."""
        n = self._tk.get_vocab_size()
        try:
            plain = self._tk.decode_batch([[i] for i in range(n)],
                                          skip_special_tokens=True)
        except Exception:
            plain = [self.decode([i]) for i in range(n)]
        pieces = [self._tk.id_to_token(i) or "" for i in range(n)]
        byte_level = any("\u0120" in t for t in pieces)  # "Ġ" marker
        metaspace = not byte_level and any(
            t.startswith("\u2581") for t in pieces)  # "▁" marker
        if byte_level:
            inv = _bytelevel_inverse()
            out = []
            for dec, t in zip(plain, pieces):
                if dec == "" or not t:
                    out.append("")  # special / empty: never eligible
                elif all(c in inv for c in t):
                    try:
                        # STRICT: a token holding a partial multi-byte
                        # UTF-8 sequence has no standalone text — marking
                        # it ineligible is conservative-correct (the mask
                        # must never admit a token whose real contribution
                        # differs from what the DFA walked)
                        out.append(bytes(inv[c] for c in t).decode("utf-8"))
                    except UnicodeDecodeError:
                        out.append("")
                else:
                    out.append(dec)
            return out
        if metaspace:
            out = []
            for dec, t in zip(plain, pieces):
                if dec == "" or not t:
                    out.append("")
                elif _SP_BYTE.fullmatch(t):
                    # SentencePiece byte-fallback "<0xHH>": the piece text
                    # lies about the contribution; ASCII bytes map to their
                    # char, partial/high bytes are ineligible (see above)
                    b = int(t[3:5], 16)
                    out.append(chr(b) if b < 0x80 else "")
                else:
                    out.append(t.replace("\u2581", " "))
            return out
        return plain

    @staticmethod
    def from_dir(path: str) -> "TokenizerWrapper":
        """Load tokenizer.json (+ chat template from tokenizer_config.json).
        A ``*.gguf`` path loads the file's embedded ggml vocab instead."""
        if path.endswith(".gguf"):
            from dynamo_tpu.llm.gguf import GGUFFile, tokenizer_from_gguf

            g = GGUFFile.parse(path)
            tk = tokenizer_from_gguf(g)
            tokens = g.metadata.get("tokenizer.ggml.tokens") or []

            def tok_at(key):
                i = g.metadata.get(key)
                return tokens[int(i)] if i is not None and int(i) < len(tokens) else None

            return TokenizerWrapper(
                tk, g.metadata.get("tokenizer.chat_template"),
                tok_at("tokenizer.ggml.bos_token_id"),
                tok_at("tokenizer.ggml.eos_token_id"))
        tk = Tokenizer.from_file(os.path.join(path, "tokenizer.json"))
        chat_template = bos = eos = None
        cfg_path = os.path.join(path, "tokenizer_config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                cfg = json.load(f)
            chat_template = cfg.get("chat_template")

            def _tok(v):
                if isinstance(v, dict):
                    return v.get("content")
                return v

            bos = _tok(cfg.get("bos_token"))
            eos = _tok(cfg.get("eos_token"))
        return TokenizerWrapper(tk, chat_template, bos, eos)


_SP_BYTE = re.compile(r"<0x[0-9A-Fa-f]{2}>")


def load_guided_vocab(tokenizer_ref: str):
    """Best-effort guided-decoding vocabulary for a worker main: returns
    None (guided requests will be refused with a clear error) when the
    tokenizer cannot be decoded, rather than failing startup."""
    try:
        return TokenizerWrapper.from_dir(tokenizer_ref).guided_vocab()
    except Exception:
        logging.getLogger("dynamo.tokenizer").warning(
            "could not decode vocab from %s; guided decoding disabled",
            tokenizer_ref, exc_info=True)
        return None


def _bytelevel_inverse() -> dict:
    """char → byte inverse of the byte-level BPE alphabet (the standard
    printable-remap table used by GPT-2-lineage tokenizers): printable
    bytes map to themselves, the rest to U+0100+offset codepoints."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(0xA1, 0xAC + 1)) + list(range(0xAE, 0xFF + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for b, c in zip(bs, cs)}


class IncrementalDecoder:
    """Stateful token→text decoder for one response stream."""

    def __init__(self, tokenizer: Tokenizer, skip_special_tokens: bool = True):
        self._tk = tokenizer
        self._stream = DecodeStream(skip_special_tokens=skip_special_tokens)

    def step(self, token_id: int) -> Optional[str]:
        """Feed one token; returns newly-decodable text (None while pending)."""
        return self._stream.step(self._tk, token_id)


DEFAULT_TEST_CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "{{ '<|' + message['role'] + '|>' }} {{ message['content'] }}\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}{{ '<|assistant|>' }}{% endif %}"
)


def make_test_tokenizer(extra_words: Optional[list[str]] = None) -> TokenizerWrapper:
    """Small deterministic whitespace WordLevel tokenizer for tests/mocker."""
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    words = [
        "<unk>", "<s>", "</s>", "<|user|>", "<|assistant|>", "<|system|>",
        "hello", "world", "the", "quick", "brown", "fox", "jumps", "over",
        "lazy", "dog", "a", "b", "c", "d", "e", "f", "g", "h", "i", "j",
        "what", "is", "capital", "of", "france", "paris", "tell", "me",
        "about", "tokens", "stream", "stop", "sequence", "test", ".", ",", "?",
    ] + (extra_words or [])
    vocab = {w: i for i, w in enumerate(dict.fromkeys(words))}
    tk = Tokenizer(WordLevel(vocab, unk_token="<unk>"))
    tk.pre_tokenizer = Whitespace()
    return TokenizerWrapper(
        tk,
        chat_template=DEFAULT_TEST_CHAT_TEMPLATE,
        bos_token="<s>",
        eos_token="</s>",
    )
