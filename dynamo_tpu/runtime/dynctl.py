"""``python -m dynamo_tpu.runtime.dynctl`` — control-plane server + ops CLI.

Default (no subcommand): run the control-plane server — a single
self-contained process replacing the reference's etcd + NATS pair for
TPU-VM deployments. Point every other process at it with
``DYN_CONTROL_PLANE=host:port``.

HA: run a second dynctl with ``--standby-of primary:port`` and set
``DYN_CONTROL_PLANE=primary:port,standby:port`` everywhere — the standby
mirrors durable state, promotes itself (fresh epoch) after sustained
primary silence, and fences/demotes the old primary if it comes back
(ref HA role: lib/runtime/src/transports/etcd.rs:35-770 replicated etcd).

Subcommands:

- ``dynctl trace <request-id>`` — stitch the request's spans fetched from
  every registered tracer over the control plane (frontend, workers) and
  print the trace tree; ``--json`` dumps the raw span list. Needs
  ``DYN_CONTROL_PLANE`` pointed at the cluster's hub.
- ``dynctl autoscale`` — live view of the closed-loop SLA autoscaler
  (docs/autoscaling.md): controller decision/SLO state, planner target,
  and the operator's desired/alive/ready/draining counts per service;
  ``--watch`` refreshes, ``--json`` dumps the raw status documents.
- ``dynctl top`` — live fleet table from the step flight recorders
  (docs/observability.md "Flight recorder"): per-worker tok/s, step
  p50/p95, anomaly counts, KV tier occupancy G1–G4, queue depths, plus
  the hub's own event counters; ``--watch`` refreshes, ``--json`` dumps.
- ``dynctl timeline <worker>`` — one worker's recent step strip with
  anomaly tags (``!`` slow, ``C`` compile, ``P`` preempt-storm, ``s``
  budget-starved, ``_`` empty bubble) and the tagged records in full;
  ``--watch`` refreshes incrementally via the ``since`` step cursor.
- ``dynctl kv [--worker] [--diff]`` — the KV index audit view
  (docs/observability.md "KV audit"): per worker, the router's
  advertised block count vs the worker's resident count, phantom /
  missing / dangling divergence with age, last heal, suspicion score and
  stale-advert pull failures; ``--diff`` adds divergent-hash samples.
- ``dynctl fleet`` — the fleet scorecard (docs/observability.md "Fleet
  scorecard"): per-class SLO rollup cross-checked against the frontend's
  own histograms, attribution reconciliation, migration outcomes, audit
  divergence/heals, autoscale decisions and hub saturation, fetched from
  a frontend's ``/v1/fleet/scorecard``; ``--watch`` refreshes, ``--json``
  dumps the raw document.
- ``dynctl why <request-id>`` — the per-request latency attribution tree
  (docs/observability.md "Attribution"): the request's spans joined with
  the serving workers' step records, every millisecond bucketed into a
  named cause (queue wait, KV transfer, compile, compute, preempt stall,
  scheduler bubble, …) plus the unattributed residual, with the tagged
  StepRecords behind each stall.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time

from dynamo_tpu.runtime.config import setup_logging
from dynamo_tpu.runtime.control_plane import ControlPlaneServer


async def amain(host: str, port: int, persist: str = None,
                persist_interval: float = 5.0, standby_of: str = None,
                takeover_after: float = 6.0, replicate_interval: float = 1.0):
    server = ControlPlaneServer(host, port, persist_path=persist,
                                persist_interval=persist_interval,
                                standby_of=standby_of,
                                takeover_after=takeover_after,
                                replicate_interval=replicate_interval)
    addr = await server.start()
    print(f"dynctl listening on {addr}"
          + (" (standby)" if server.is_standby else ""), flush=True)

    stop = asyncio.Event()
    try:
        import signal
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop.set)
    except (ImportError, NotImplementedError):
        pass
    try:
        await stop.wait()  # SIGTERM → graceful stop → final state flush
    finally:
        await server.stop()


async def trace_amain(request_id: str, as_json: bool, timeout: float) -> int:
    """Fetch + stitch + print one request's distributed trace."""
    from dynamo_tpu.observability import fetch_trace, get_tracer, stitch
    from dynamo_tpu.runtime import DistributedRuntime

    runtime = await DistributedRuntime.create()
    try:
        spans = {d["span_id"]: d
                 for d in await fetch_trace(runtime.plane, request_id,
                                            timeout=timeout)}
        # a dynctl running inside a serving process (tests) also sees its
        # own buffer; standalone CLI runs contribute nothing here
        for s in get_tracer().spans_for(request_id):
            spans.setdefault(s.span_id, s.to_dict())
        ordered = sorted(spans.values(),
                         key=lambda d: d.get("start") or 0.0)
        if not ordered:
            print(f"no spans recorded for request {request_id!r} "
                  "(is DYN_CONTROL_PLANE set, and did the request run "
                  "recently enough to still be in the span ring buffers?)",
                  file=sys.stderr)
            return 1
        if as_json:
            print(json.dumps(ordered, indent=2))
            return 0
        t0 = min(d.get("start") or 0.0 for d in ordered)
        print(f"trace {ordered[0].get('trace_id')} "
              f"(request {request_id}): {len(ordered)} spans")
        for d in stitch(ordered):
            dur = ((d.get("end") or d.get("start") or 0.0)
                   - (d.get("start") or 0.0))
            off = (d.get("start") or 0.0) - t0
            attrs = " ".join(f"{k}={v}" for k, v in
                             (d.get("attributes") or {}).items())
            mark = "" if d.get("status", "ok") == "ok" else " [ERROR]"
            print(f"  {'  ' * d['depth']}{d['name']:<24s} "
                  f"+{off * 1000:8.1f}ms {dur * 1000:8.1f}ms "
                  f"[{d.get('service', '')}]{mark} {attrs}".rstrip())
        return 0
    finally:
        await runtime.shutdown()


async def autoscale_amain(namespace: str, as_json: bool,
                          watch: float = 0.0) -> int:
    """Render the autoscale loop's live state from its control-plane keys."""
    from dynamo_tpu.autoscale.controller import (
        AUTOSCALE_STATUS_KEY, OPERATOR_STATUS_KEY,
    )
    from dynamo_tpu.planner.virtual_connector import SCALE_KEY
    from dynamo_tpu.runtime import DistributedRuntime

    runtime = await DistributedRuntime.create()

    async def read(key_tpl: str):
        raw = await runtime.plane.kv_get(
            key_tpl.format(namespace=namespace))
        return json.loads(raw) if raw else None

    def fmt_age(ts) -> str:
        import time as _t

        return f"{max(0.0, _t.time() - ts):.0f}s ago" if ts else "never"

    try:
        while True:
            ctl = await read(AUTOSCALE_STATUS_KEY)
            op = await read(OPERATOR_STATUS_KEY)
            target = await read(SCALE_KEY)
            if as_json:
                print(json.dumps({"autoscale": ctl, "operator": op,
                                  "plannerTarget": target}, indent=2))
            else:
                print(f"autoscale status (namespace {namespace!r})")
                if ctl is None and op is None and target is None:
                    print("  nothing published — is the autoscaler/operator "
                          "running against this control plane?")
                if ctl:
                    d = ctl.get("desired") or {}
                    r = ctl.get("ready") or {}
                    last = ctl.get("lastDecision") or {}
                    c = ctl.get("counters") or {}
                    print(f"  controller  updated {fmt_age(ctl.get('ts'))}: "
                          f"desired prefill={d.get('prefill')} "
                          f"decode={d.get('decode')}  ready={r or '-'}  "
                          f"backlog={ctl.get('queueDepth')}  "
                          f"workers={ctl.get('workers')}")
                    print(f"  last decision: {last.get('direction')} "
                          f"({last.get('reason')})  "
                          f"ups={c.get('scaleUps')} downs={c.get('scaleDowns')} "
                          f"deferred={c.get('deferredUnready')} "
                          f"cooldown-held={c.get('heldCooldown')} "
                          f"scrape-failures={c.get('scrapeFailures')}")
                    for cls, b in sorted((ctl.get("slo") or {}).items()):
                        mark = "OK" if b.get("ok") else "BREACH"
                        burn = b.get("burn")
                        burn_s = (f"  burn {burn:.2f}x"
                                  if burn is not None else "")
                        print(f"  slo {cls:<12s} ttft p95 "
                              f"{b.get('ttft_p95_ms')}ms / "
                              f"target {b.get('target_ms')}ms  "
                              f"[{mark}]{burn_s}")
                if target:
                    print(f"  planner key: prefill={target.get('prefill')} "
                          f"decode={target.get('decode')} "
                          f"(rev {target.get('revision')})")
                if op:
                    for name, svc in sorted(
                            (op.get("services") or {}).items()):
                        role = svc.get("plannerRole") or "-"
                        gate = "gated" if svc.get("readinessGated") else "ungated"
                        print(f"  {name:<12s} role={role:<8s} "
                              f"desired={svc.get('desired')} "
                              f"alive={svc.get('alive')} "
                              f"ready={svc.get('ready')} "
                              f"draining={svc.get('draining')} "
                              f"restarts={svc.get('restarts')} [{gate}]")
                    print(f"  drains: {op.get('drainsCompleted', 0)} graceful"
                          f", {op.get('drainsKilled', 0)} killed, "
                          f"{op.get('drainSecondsTotal', 0.0)}s total")
            if not watch:
                return 0 if (ctl or op or target) else 1
            await asyncio.sleep(watch)
            print()
    finally:
        await runtime.shutdown()


async def top_amain(as_json: bool, watch: float = 0.0,
                    timeout: float = 2.0) -> int:
    """Live fleet table from every worker's flight recorder summary."""
    from dynamo_tpu.observability import fetch_fleet_steps
    from dynamo_tpu.observability.scorecard import HubSaturationTracker
    from dynamo_tpu.runtime import DistributedRuntime

    runtime = await DistributedRuntime.create()
    # hub-saturation footer: rpc ops/s between refreshes vs the measured
    # ceiling (same ratio dynamo_hub_saturation_ratio{kind="rpc"} exports)
    sat = HubSaturationTracker()

    def fmt_anoms(anoms: dict) -> str:
        labels = (("slow-step", "slow"), ("compile-steady", "steady"),
                  ("compile", "compile"), ("preempt-storm", "storm"),
                  ("budget-starved", "starved"), ("empty-step", "empty"))
        parts = [f"{short}={anoms[k]}" for k, short in labels
                 if anoms.get(k)]
        return " ".join(parts) or "-"

    try:
        while True:
            workers = await fetch_fleet_steps(runtime.plane, n=0,
                                              timeout=timeout)
            hub = None
            if hasattr(runtime.plane, "hub_stats"):
                try:
                    hub = await runtime.plane.hub_stats()
                except Exception:
                    pass
            if as_json:
                print(json.dumps({"workers": workers, "hub": hub},
                                 indent=2))
            else:
                if not workers:
                    print("no flight recorders registered — are workers "
                          "running against this control plane (and is "
                          "DYN_CONTROL_PLANE set)?")
                else:
                    hdr = (f"{'worker':<28s} {'steps':>7s} {'tok/s':>8s} "
                           f"{'p50ms':>8s} {'p95ms':>8s} "
                           f"{'g1/g2/g3/g4':>15s} {'w/s/r':>8s}  anomalies")
                    print(hdr)
                    for name in sorted(workers):
                        s = workers[name].get("summary") or {}
                        t = s.get("kv_tiers") or {}
                        tiers = "/".join(str(t.get(k, 0))
                                         for k in ("g1", "g2", "g3", "g4"))
                        queues = (f"{s.get('waiting', 0)}/"
                                  f"{s.get('swapped', 0)}/"
                                  f"{s.get('running', 0)}")
                        print(f"{name:<28s} {s.get('steps_total', 0):>7d} "
                              f"{s.get('tok_s', 0.0):>8.1f} "
                              f"{s.get('wall_p50_ms', 0.0):>8.2f} "
                              f"{s.get('wall_p95_ms', 0.0):>8.2f} "
                              f"{tiers:>15s} {queues:>8s}  "
                              f"{fmt_anoms(s.get('anomalies') or {})}")
                if hub:
                    ev = hub.get("events") or {}
                    pub = hub.get("publish_seconds") or {}
                    mean_us = (pub["sum"] / pub["count"] * 1e6
                               if pub.get("count") else 0.0)
                    sat.sample(hub)
                    if not watch and sat.rates().get("rpc") is None:
                        # one-shot run: a rate needs two samples — take a
                        # short second one instead of printing nothing
                        await asyncio.sleep(0.3)
                        try:
                            sat.sample(await runtime.plane.hub_stats())
                        except Exception:
                            pass
                    ratio = sat.ratios().get("rpc")
                    sat_txt = (f"  saturation {ratio * 100:.1f}% of "
                               f"{sat.rpc_ceiling:.0f} rpc/s"
                               if ratio is not None else "")
                    print(f"hub: "
                          + " ".join(f"{k}={v}" for k, v in sorted(ev.items()))
                          + f"  publish mean {mean_us:.0f}us over "
                            f"{pub.get('count', 0)} events" + sat_txt)
                    # KV event-stream health (docs/observability.md "KV
                    # audit"): is the radix's feed intact, truncating, or
                    # forcing resyncs?
                    kv = (hub.get("streams") or {}).get("kv_events")
                    if kv:
                        print(f"kv_events: last seq {kv.get('last_seq', 0)} "
                              f"(retained from {kv.get('first_seq', 1)})  "
                              f"truncated {kv.get('truncated', 0)}  "
                              f"resyncs requested "
                              f"{hub.get('resyncs_requested', 0)}")
            if not watch:
                return 0 if workers else 1
            await asyncio.sleep(watch)
            print()
    finally:
        await runtime.shutdown()


#: timeline strip symbols, highest-priority tag wins per record
_STRIP = (("empty-step", "_"), ("preempt-storm", "P"),
          ("compile-steady", "C"), ("compile", "c"), ("slow-step", "!"),
          ("budget-starved", "s"))


def _print_timeline(name: str, entry: dict) -> None:
    steps = entry.get("steps") or []
    summary = entry.get("summary") or {}
    marks = ""
    if entry.get("restarted"):
        marks += "  [recorder restarted — cursor reset]"
    if entry.get("gap"):
        marks += f"  [{entry['gap']} records skipped — raise -n]"
    print(f"{name}: {len(steps)} recent steps "
          f"(p95 {summary.get('wall_p95_ms', 0.0)}ms, "
          f"anomalies {summary.get('anomalies') or {}}){marks}")
    strip = []
    for rec in steps:
        tags = set(rec.get("tags") or [])
        sym = "."
        for tag, ch in _STRIP:
            if tag in tags:
                sym = ch
                break
        strip.append(sym)
    print("  " + "".join(strip))
    for rec in steps:
        if not rec.get("tags"):
            continue
        extras = " ".join(
            f"{k}={rec[k]}" for k in
            ("compile_sig", "compile_s", "preempt_swap",
             "preempt_recompute", "starved_decode", "waiting",
             "swapped", "profile_path") if rec.get(k))
        print(f"  #{rec.get('seq'):<7d} {rec.get('kind', ''):<12s} "
              f"{rec.get('wall_ms', 0.0):>9.2f}ms "
              f"dec={rec.get('decode_rows', 0)} "
              f"chunks={rec.get('prefill_chunks', 0)} "
              f"[{','.join(rec.get('tags'))}] {extras}".rstrip())


async def timeline_amain(worker: str, n: int, as_json: bool,
                         timeout: float = 2.0, watch: float = 0.0) -> int:
    """Recent step strip + tagged records for one worker (substring match
    on the fleet key, e.g. ``backend`` or the lease hex). ``--watch``
    polls incrementally: the wire ``since`` carries the LOWEST cursor of
    the matched workers (each recorder's seq counter is independent, so
    one shared high-water mark would freeze the lower-seq workers), and
    the per-worker cursors filter client-side on top."""
    from dynamo_tpu.observability import fetch_fleet_steps
    from dynamo_tpu.runtime import DistributedRuntime

    runtime = await DistributedRuntime.create()
    cursors: dict[str, int] = {}
    first = True
    try:
        while True:
            wire_since = min(cursors.values()) if cursors else 0
            workers = await fetch_fleet_steps(runtime.plane, n=n,
                                              timeout=timeout,
                                              since=wire_since)
            matches = {k: v for k, v in workers.items() if worker in k}
            if not matches and first:
                print(f"no flight recorder matches {worker!r} "
                      f"(known: {sorted(workers) or 'none'})",
                      file=sys.stderr)
                return 1
            first = False
            for key, entry in matches.items():
                cur = cursors.get(key, 0)
                last = int((entry.get("summary") or {}).get("last_seq")
                           or 0)
                if 0 < last < cur:
                    # the worker's recorder restarted (fresh seq counter):
                    # reset this cursor, and the NEXT poll's wire since
                    # (min over cursors) drops low enough to refetch it —
                    # otherwise the server-side filter would hide the new
                    # life's records forever
                    cursors[key] = cur = 0
                    entry["restarted"] = True
                steps = [rec for rec in entry.get("steps") or []
                         if int(rec.get("seq") or 0) > cur]
                entry["steps"] = steps
                if steps:
                    if cur and int(steps[0].get("seq") or 0) > cur + 1:
                        # more new records than -n fetched: mark the hole
                        # instead of rendering a silently-continuous strip
                        entry["gap"] = int(steps[0]["seq"]) - cur - 1
                    cursors[key] = int(steps[-1].get("seq") or 0)
            if as_json:
                print(json.dumps(matches, indent=2))
            else:
                for name in sorted(matches):
                    _print_timeline(name, matches[name])
            if not watch:
                return 0
            await asyncio.sleep(watch)
            print()
    finally:
        await runtime.shutdown()


async def why_amain(request_id: str, as_json: bool, records: int = 2048,
                    timeout: float = 2.0) -> int:
    """Fetch + join + print one request's latency attribution tree."""
    from dynamo_tpu.observability.attribution import gather_attribution
    from dynamo_tpu.runtime import DistributedRuntime

    runtime = await DistributedRuntime.create()
    try:
        doc = await gather_attribution(request_id, runtime=runtime,
                                       records=records, timeout=timeout)
        if doc is None:
            print(f"no spans or step records mention {request_id!r} "
                  "(is DYN_CONTROL_PLANE set, and is the request still "
                  "inside the span/step ring windows?)", file=sys.stderr)
            return 1
        if as_json:
            print(json.dumps(doc, indent=2))
            return 0
        flags = []
        if not doc.get("trace_sampled", True):
            flags.append("trace sampled out — flight-only decomposition")
        if doc.get("incomplete"):
            flags.append("INCOMPLETE: step ring wrapped over part of the "
                         "request's interval")
        print(f"request {doc['request_id']}  e2e {doc['e2e_ms']:.1f}ms  "
              f"qos={doc.get('qos')}  workers={doc.get('workers')}")
        for f in flags:
            print(f"  ! {f}")
        for phase in ("ttft", "itl"):
            total = doc.get(f"{phase}_ms") or 0.0
            buckets = doc.get(phase) or {}
            if not buckets and not total:
                continue
            print(f"  {phase} {total:.1f}ms")
            for bucket, ms in sorted(buckets.items(),
                                     key=lambda kv: -kv[1]):
                if ms <= 0:
                    continue
                pct = 100.0 * ms / total if total else 0.0
                print(f"    {bucket:<16s} {ms:>9.1f}ms {pct:5.1f}%")
                for ev in (doc.get("evidence") or {}).get(bucket, [])[-3:]:
                    bits = " ".join(f"{k}={ev[k]}" for k in
                                    ("kind", "wall_ms", "tags",
                                     "compile_sig", "profile_path")
                                    if ev.get(k))
                    print(f"      · step #{ev.get('seq')} {bits}")
        res = doc.get("residual_ms") or 0.0
        e2e = doc.get("e2e_ms") or 0.0
        print(f"  residual {res:.1f}ms "
              f"({100.0 * res / e2e if e2e else 0.0:.1f}% of e2e)")
        return 0
    finally:
        await runtime.shutdown()


async def kv_amain(worker: str, diff: bool, as_json: bool,
                   watch: float = 0.0, timeout: float = 2.0) -> int:
    """``dynctl kv`` — the KV index audit view (docs/observability.md
    "KV audit"): per worker, the router's advertised block count vs the
    worker's resident count (live digest), divergence classification +
    age, last heal, suspicion, and stale-advert pull failures. The audit
    status comes from the routers' published docs (public/kvaudit/...);
    resident counts are fetched live from each worker's kv_digest
    endpoint so the view works even before any auditor has run."""
    from dynamo_tpu.observability.kvaudit import (fetch_kv_chain,
                                                  fetch_kv_digest,
                                                  list_digest_workers,
                                                  u64_hex)
    from dynamo_tpu.runtime import DistributedRuntime

    runtime = await DistributedRuntime.create()
    try:
        while True:
            statuses = {}
            try:
                for key, value in (await runtime.plane.kv_get_prefix(
                        "public/kvaudit/")).items():
                    try:
                        st = json.loads(value)
                    except Exception:
                        continue
                    # a stopped auditor deletes its doc; a CRASHED one
                    # can't — flag anything older than 3 intervals so a
                    # dead fleet's counts never read as live
                    age = time.time() - float(st.get("ts") or 0)
                    if age > 3 * float(st.get("interval_s") or 30.0):
                        st["stale_s"] = round(age, 1)
                    # key = public/kvaudit/<stream>/<replica>
                    statuses[key[len("public/kvaudit/"):]] = st
            except Exception:
                pass
            endpoints = await list_digest_workers(runtime.plane)
            digests = {}
            for wid in endpoints:
                d = await fetch_kv_digest(runtime.plane, wid, timeout)
                if d is not None:
                    digests[u64_hex(wid)] = d
            if as_json:
                print(json.dumps({"audit": statuses, "digests": digests},
                                 indent=2))
            else:
                # one row per worker: audit status merged with the live
                # digest (live wins for "resident now")
                rows: dict[str, dict] = {}
                for stream, st in statuses.items():
                    if st.get("stale_s"):
                        print(f"warning: audit status for stream "
                              f"{stream!r} is {st['stale_s']}s old "
                              f"(auditor crashed?) — counts below may "
                              f"describe a dead fleet")
                    for whex, w in (st.get("workers") or {}).items():
                        rows[whex] = dict(w)
                for whex, d in digests.items():
                    rows.setdefault(whex, {})["resident_now"] = (
                        d.get("servable") or {}).get("count")
                    rows[whex]["tiers"] = {
                        t: v.get("count", 0)
                        for t, v in (d.get("tiers") or {}).items()}
                shown = {k: v for k, v in rows.items()
                         if not worker or worker in k}
                if not shown:
                    print("no kv_digest endpoints or audit status found — "
                          "are workers (and a kv-mode router) running "
                          "against this control plane?")
                else:
                    print(f"{'worker':<18s} {'advert':>7s} {'resident':>9s} "
                          f"{'phantom':>8s} {'missing':>8s} {'dangling':>9s} "
                          f"{'div-age':>8s} {'heal':>9s} {'susp':>5s} "
                          f"{'stale':>6s}  tiers g1/g2/g3/g4")
                    for whex in sorted(shown):
                        w = shown[whex]
                        res = w.get("resident_now",
                                    w.get("resident_blocks"))
                        t = w.get("tiers") or {}
                        tiers = "/".join(str(t.get(k, 0)) for k in
                                         ("g1", "g2", "g3", "g4"))
                        heal = w.get("last_heal_s_ago")
                        print(f"{whex:<18s} "
                              f"{w.get('advertised_blocks', 0):>7} "
                              f"{res if res is not None else '-':>9} "
                              f"{w.get('phantom', 0):>8} "
                              f"{w.get('missing', 0):>8} "
                              f"{w.get('dangling', 0):>9} "
                              f"{w.get('divergence_age_s', 0.0):>7.1f}s "
                              f"{(f'{heal:.0f}s ago' if heal is not None else 'never'):>9s} "
                              f"{w.get('suspicion', 0):>5} "
                              f"{w.get('stale_adverts', 0):>6}  {tiers}")
                        if diff and w.get("samples"):
                            for kind, hs in sorted(w["samples"].items()):
                                if hs:
                                    print(f"    {kind}: "
                                          + " ".join(f"{h:x}" for h in hs))
                if diff and worker:
                    # live chain fetch for the named worker: the full
                    # resident/anchored view, not just the last audit's
                    # samples
                    for wid in endpoints:
                        whex = u64_hex(wid)
                        if worker not in whex:
                            continue
                        ch = await fetch_kv_chain(runtime.plane, wid,
                                                  timeout)
                        if ch:
                            print(f"  {whex} live chain: "
                                  f"{ch.get('resident_total', 0)} resident, "
                                  f"{len(ch.get('anchored') or ())} "
                                  f"root-anchored")
            if not watch:
                return 0 if (statuses or digests) else 1
            await asyncio.sleep(watch)
            print()
    finally:
        await runtime.shutdown()


def _kv_main(argv: list[str]) -> None:
    ap = argparse.ArgumentParser(
        prog="dynctl kv",
        description="KV index audit view: advertised vs resident blocks, "
                    "divergence, heals, suspicion per worker")
    ap.add_argument("--worker", default="",
                    help="filter by worker lease-hex substring")
    ap.add_argument("--diff", action="store_true",
                    help="show divergent-hash samples (and, with "
                         "--worker, the live chain summary)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--watch", type=float, default=0.0, metavar="SECONDS",
                    help="refresh every N seconds (0 = one-shot)")
    ap.add_argument("--timeout", type=float, default=2.0)
    args = ap.parse_args(argv)
    raise SystemExit(asyncio.run(
        kv_amain(args.worker, args.diff, args.json, args.watch,
                 args.timeout)))


def _top_main(argv: list[str]) -> None:
    ap = argparse.ArgumentParser(
        prog="dynctl top",
        description="live fleet table from the step flight recorders")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--watch", type=float, default=0.0, metavar="SECONDS",
                    help="refresh every N seconds (0 = one-shot)")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-worker fetch timeout (seconds)")
    args = ap.parse_args(argv)
    raise SystemExit(asyncio.run(
        top_amain(args.json, args.watch, args.timeout)))


def _timeline_main(argv: list[str]) -> None:
    ap = argparse.ArgumentParser(
        prog="dynctl timeline",
        description="recent step strip + tagged records for one worker")
    ap.add_argument("worker", help="fleet key substring "
                                   "(component name or lease hex)")
    ap.add_argument("-n", type=int, default=120,
                    help="recent records to fetch (default 120)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--timeout", type=float, default=2.0)
    ap.add_argument("--watch", type=float, default=0.0, metavar="SECONDS",
                    help="refresh every N seconds via the since cursor "
                         "(0 = one-shot)")
    args = ap.parse_args(argv)
    raise SystemExit(asyncio.run(
        timeline_amain(args.worker, args.n, args.json, args.timeout,
                       args.watch)))


def _why_main(argv: list[str]) -> None:
    ap = argparse.ArgumentParser(
        prog="dynctl why",
        description="per-request latency attribution: spans joined with "
                    "the serving workers' step records")
    ap.add_argument("request_id")
    ap.add_argument("--records", type=int, default=2048,
                    help="step records to fetch per worker (default 2048)")
    ap.add_argument("--json", action="store_true",
                    help="dump the raw attribution document")
    ap.add_argument("--timeout", type=float, default=2.0)
    args = ap.parse_args(argv)
    raise SystemExit(asyncio.run(
        why_amain(args.request_id, args.json, args.records, args.timeout)))


async def fleet_amain(url: str, as_json: bool, watch: float = 0.0,
                      timeout: float = 5.0) -> int:
    """The fleet scorecard (docs/observability.md "Fleet scorecard"):
    GET /v1/fleet/scorecard off a frontend and render the joined
    per-class SLO / attribution / migration / audit / autoscale / hub
    rollup with its falsifiability checks."""
    import aiohttp

    from dynamo_tpu.observability.scorecard import render_scorecard

    async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=timeout)) as session:
        while True:
            try:
                async with session.get(
                        f"{url.rstrip('/')}/v1/fleet/scorecard") as resp:
                    doc = await resp.json()
            except Exception as e:
                print(f"scorecard fetch failed: {e}", file=sys.stderr)
                return 1
            if as_json:
                print(json.dumps(doc, indent=2))
            else:
                print(render_scorecard(doc))
            if not watch:
                return 0 if doc.get("ok") else 1
            await asyncio.sleep(watch)
            print()


def _fleet_main(argv: list[str]) -> None:
    ap = argparse.ArgumentParser(
        prog="dynctl fleet",
        description="render a frontend's fleet scorecard "
                    "(/v1/fleet/scorecard)")
    ap.add_argument("--url", default="http://127.0.0.1:8000",
                    help="frontend base URL (default http://127.0.0.1:8000)")
    ap.add_argument("--json", action="store_true",
                    help="dump the raw scorecard document")
    ap.add_argument("--watch", type=float, default=0.0, metavar="SECONDS",
                    help="refresh every N seconds (0 = one-shot)")
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)
    raise SystemExit(asyncio.run(
        fleet_amain(args.url, args.json, args.watch, args.timeout)))


async def frontends_amain(url: str, as_json: bool, watch: float = 0.0,
                          timeout: float = 5.0) -> int:
    """Front-door census (docs/robustness.md "Front door"): GET
    /v1/fleet/frontends off any one replica and list every live frontend
    lease with drain-aware readiness. Exit 0 only when at least one
    replica is ready."""
    import aiohttp

    async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=timeout)) as session:
        while True:
            try:
                async with session.get(
                        f"{url.rstrip('/')}/v1/fleet/frontends") as resp:
                    doc = await resp.json()
            except Exception as e:
                print(f"frontend census fetch failed: {e}", file=sys.stderr)
                return 1
            if as_json:
                print(json.dumps(doc, indent=2))
            else:
                rows = doc.get("frontends") or []
                print(f"{'replica':<18s}{'url':<32s}{'pid':>8s}"
                      f"{'up_s':>8s}  state")
                now = time.time()
                for fe in rows:
                    up = now - fe["started"] if fe.get("started") else 0.0
                    state = "ready" if fe.get("ready", True) else "draining"
                    if fe.get("self"):
                        state += " *"
                    print(f"{str(fe.get('replica')):<18s}"
                          f"{str(fe.get('url')):<32s}"
                          f"{str(fe.get('pid') or '-'):>8s}"
                          f"{up:>8.1f}  {state}")
                print(f"{doc.get('ready', 0)}/{doc.get('count', 0)} ready")
            if not watch:
                return 0 if doc.get("ready") else 1
            await asyncio.sleep(watch)
            print()


def _frontends_main(argv: list[str]) -> None:
    ap = argparse.ArgumentParser(
        prog="dynctl frontends",
        description="list live frontend replicas with readiness "
                    "(/v1/fleet/frontends)")
    ap.add_argument("--url", default="http://127.0.0.1:8000",
                    help="any frontend base URL "
                         "(default http://127.0.0.1:8000)")
    ap.add_argument("--json", action="store_true",
                    help="dump the raw census document")
    ap.add_argument("--watch", type=float, default=0.0, metavar="SECONDS",
                    help="refresh every N seconds (0 = one-shot)")
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)
    raise SystemExit(asyncio.run(
        frontends_amain(args.url, args.json, args.watch, args.timeout)))


async def sessions_amain(url: str, as_json: bool, watch: float = 0.0,
                         timeout: float = 5.0) -> int:
    """Live session registry view (docs/sessions.md): GET /v1/sessions off
    a frontend and render id / turns / affinity worker / idle / parked
    state. Exit 0 when the registry is enabled (even if empty)."""
    import aiohttp

    async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=timeout)) as session:
        while True:
            try:
                async with session.get(
                        f"{url.rstrip('/')}/v1/sessions") as resp:
                    doc = await resp.json()
            except Exception as e:
                print(f"session registry fetch failed: {e}", file=sys.stderr)
                return 1
            if as_json:
                print(json.dumps(doc, indent=2))
            else:
                rows = doc.get("sessions") or []
                print(f"{'session':<26s}{'model':<16s}{'turns':>6s}"
                      f"{'worker':>18s}{'idle_s':>8s}{'parked':>8s}"
                      f"{'restored':>9s}  state")
                for s in rows:
                    state = ("active" if s.get("active")
                             else "parked" if s.get("parked") else "idle")
                    print(f"{str(s.get('id'))[:25]:<26s}"
                          f"{str(s.get('model'))[:15]:<16s}"
                          f"{s.get('turns', 0):>6d}"
                          f"{str(s.get('worker') or '-'):>18s}"
                          f"{s.get('idle_s', 0.0):>8.1f}"
                          f"{s.get('parked_blocks', 0):>8d}"
                          f"{s.get('restored_blocks', 0):>9d}  {state}")
                print(f"{doc.get('count', 0)}/{doc.get('cap', '-')} sessions"
                      f" (ttl {doc.get('ttl_s', '-')}s, park after "
                      f"{doc.get('park_after_s', '-')}s)"
                      + ("" if doc.get("enabled", True)
                         else " — registry DISABLED"))
            if not watch:
                return 0 if doc.get("enabled", True) else 1
            await asyncio.sleep(watch)
            print()


def _sessions_main(argv: list[str]) -> None:
    ap = argparse.ArgumentParser(
        prog="dynctl sessions",
        description="show a frontend's live session registry "
                    "(/v1/sessions: turns, affinity, parked KV)")
    ap.add_argument("--url", default="http://127.0.0.1:8000",
                    help="frontend base URL (default http://127.0.0.1:8000)")
    ap.add_argument("--json", action="store_true",
                    help="dump the raw registry snapshot")
    ap.add_argument("--watch", type=float, default=0.0, metavar="SECONDS",
                    help="refresh every N seconds (0 = one-shot)")
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)
    raise SystemExit(asyncio.run(
        sessions_amain(args.url, args.json, args.watch, args.timeout)))


def _autoscale_main(argv: list[str]) -> None:
    ap = argparse.ArgumentParser(
        prog="dynctl autoscale",
        description="show the closed-loop SLA autoscaler's live state")
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--json", action="store_true",
                    help="dump the raw status documents")
    ap.add_argument("--watch", type=float, default=0.0, metavar="SECONDS",
                    help="refresh every N seconds (0 = one-shot)")
    args = ap.parse_args(argv)
    raise SystemExit(asyncio.run(
        autoscale_amain(args.namespace, args.json, args.watch)))


def _trace_main(argv: list[str]) -> None:
    ap = argparse.ArgumentParser(
        prog="dynctl trace",
        description="stitch and print a request's distributed trace")
    ap.add_argument("request_id")
    ap.add_argument("--json", action="store_true",
                    help="dump raw span dicts instead of the tree view")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-tracer fetch timeout (seconds)")
    args = ap.parse_args(argv)
    raise SystemExit(asyncio.run(
        trace_amain(args.request_id, args.json, args.timeout)))


def main():
    setup_logging()
    if len(sys.argv) > 1 and sys.argv[1] == "trace":
        _trace_main(sys.argv[2:])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "autoscale":
        _autoscale_main(sys.argv[2:])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "top":
        _top_main(sys.argv[2:])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "timeline":
        _timeline_main(sys.argv[2:])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "why":
        _why_main(sys.argv[2:])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "kv":
        _kv_main(sys.argv[2:])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "fleet":
        _fleet_main(sys.argv[2:])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "frontends":
        _frontends_main(sys.argv[2:])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "sessions":
        _sessions_main(sys.argv[2:])
        return
    ap = argparse.ArgumentParser(description="dynamo-tpu control plane server")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=6650)
    ap.add_argument("--persist", default=None, metavar="FILE",
                    help="durable-state file: discovery keys, object store "
                         "and stream tails survive a restart (leases do "
                         "not); snapshotted every --persist-interval s, "
                         "flushed on SIGTERM")
    ap.add_argument("--persist-interval", type=float, default=5.0)
    ap.add_argument("--standby-of", default=None, metavar="HOST:PORT",
                    help="run as a warm standby of this primary: mirror its "
                         "durable state, reject client ops, and promote to "
                         "primary (fresh epoch) after --takeover-after s of "
                         "primary silence; point clients at "
                         "DYN_CONTROL_PLANE=primary,standby")
    ap.add_argument("--takeover-after", type=float, default=6.0)
    ap.add_argument("--replicate-interval", type=float, default=1.0)
    args = ap.parse_args()
    asyncio.run(amain(args.host, args.port, args.persist,
                      args.persist_interval, args.standby_of,
                      args.takeover_after, args.replicate_interval))


if __name__ == "__main__":
    main()
