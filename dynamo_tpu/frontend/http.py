"""OpenAI-compatible HTTP server with SSE streaming.

Rebuild of the reference's axum service (ref: lib/llm/src/http/service/
service_v2.rs:125-420, openai.rs:209-1106): routes

- ``POST /v1/chat/completions`` (stream + non-stream)
- ``POST /v1/completions``
- ``POST /v1/embeddings``
- ``POST /v1/responses``        — Responses API lowered onto the chat chain
- ``GET  /v1/models``
- ``GET  /health`` / ``/live``  — liveness + model readiness
- ``GET  /metrics``             — Prometheus text exposition

Streaming uses SSE (``data: {chunk}\\n\\n`` … ``data: [DONE]``) with client
disconnect detection that cancels the request context so generation aborts on
the worker (ref: http/service/disconnect.rs).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from typing import Optional

from aiohttp import web

from dynamo_tpu.llm.discovery import ModelManager
from dynamo_tpu.llm.pipeline import aggregate_chat_stream, aggregate_completion_stream
from dynamo_tpu.protocols import Annotated
from dynamo_tpu.protocols.openai import (
    RequestError,
    error_body,
    gen_request_id,
    model_entry,
    parse_chat_request,
    parse_completion_request,
    parse_responses_request,
    response_msg_id,
    response_object,
)
from dynamo_tpu.observability import fetch_trace, get_tracer
from dynamo_tpu.qos import (CLASS_RANK, DEFAULT_TENANT, QosConfig,
                            normalize_priority)
from dynamo_tpu.qos.quota import DrainRateEstimator, TenantQuotas
from dynamo_tpu.runtime.context import (
    Context,
    DeadlineExceededError,
    OverloadedError,
    StreamError,
)
from dynamo_tpu.runtime.control_plane import NoRespondersError
from dynamo_tpu.runtime.metrics import MetricsRegistry, render_registries
from dynamo_tpu.sessions import (SessionConfig, SessionRegistry,
                                 UnknownResponseError)

# SSE writers iterate _batched(stream) instead of the raw stream so chunks
# that pile up while a socket write is in flight coalesce into ONE write —
# within an engine step, every sequence's chunk arrives back-to-back, and
# the per-write syscall/async overhead is paid once per step, not per
# token. Bounded queue: a slow client still backpressures the worker.
from dynamo_tpu.runtime.streams import batched as _batched

logger = logging.getLogger("dynamo.http")


class _StreamTiming:
    """TTFT/ITL phase accounting shared by BOTH SSE paths (chat/completions
    and responses) — one implementation so the SLO series can never diverge
    by route. Epoch timestamps so the spans stitch with worker-side spans."""

    def __init__(self, service: "HttpService", route: str, t0_perf: float):
        self._svc = service
        self.route = route
        self.t0_epoch = time.time() - (time.perf_counter() - t0_perf)
        self.t_first: Optional[float] = None
        self.t_last: Optional[float] = None
        self.n_chunks = 0
        self._itl = service.tracer.metrics.histogram("itl_seconds")

    def tick(self) -> bool:
        """Mark one streamed output chunk; True when it was the first.
        Each inter-chunk gap feeds dynamo_itl_seconds."""
        now = time.time()
        first = self.t_first is None
        if first:
            self.t_first = now
        elif self.t_last is not None:
            self._itl.observe(now - self.t_last)
        self.t_last = now
        self.n_chunks += 1
        return first

    def finish(self, ctx) -> None:
        """Record the retroactive "ttft" (arrival → first chunk) and "itl"
        (first → last chunk) phase spans."""
        if self.t_first is None:
            return
        tracer = self._svc.tracer
        tracer.record("ttft", ctx, start=self.t0_epoch, end=self.t_first,
                      service="frontend", route=self.route)
        if self.t_last is not None and self.n_chunks > 1:
            dur = self.t_last - self.t_first
            tracer.record("itl", ctx, start=self.t_first, end=self.t_last,
                          service="frontend", route=self.route,
                          chunks=self.n_chunks,
                          mean_itl_s=round(dur / (self.n_chunks - 1), 6))


class HttpService:
    def __init__(
        self,
        manager: ModelManager,
        metrics: Optional[MetricsRegistry] = None,
        host: str = "0.0.0.0",
        port: int = 8000,
        tls_cert_path: Optional[str] = None,
        tls_key_path: Optional[str] = None,
        runtime=None,
        replica: Optional[str] = None,
    ):
        self.manager = manager
        #: replica identity for multi-frontend deployments (docs/
        #: robustness.md "Front door"): --replica-id / DYN_FRONTEND_REPLICA
        #: / the operator's DYN_POD_NAME. None = classic single-frontend
        #: mode — no discovery lease, no replica metric label, /metrics
        #: byte-identical to a replica-unaware build.
        self.replica = (replica or os.environ.get("DYN_FRONTEND_REPLICA")
                        or os.environ.get("DYN_POD_NAME") or None)
        if metrics is None and self.replica:
            # every sample this process exports carries its replica label
            # so a fleet scrape of N frontends sums instead of clobbering
            metrics = MetricsRegistry(
                default_labels={"replica": self.replica})
        self.metrics = metrics or MetricsRegistry()
        self._frontend_key: Optional[str] = None
        self._started_at = time.time()
        #: optional DistributedRuntime — lets /v1/traces/{id} stitch spans
        #: fetched from workers over the control plane (None = local only)
        self.runtime = runtime
        #: optional TLS (ref: service_v2.rs:132 enable_tls/cert/key) —
        #: both paths or neither
        if bool(tls_cert_path) != bool(tls_key_path):
            raise ValueError("TLS needs BOTH --tls-cert-path and "
                             "--tls-key-path")
        self.tls_cert_path = tls_cert_path
        self.tls_key_path = tls_key_path
        #: bearer token gating destructive admin routes (clear_kv_blocks);
        #: unset = open, matching the reference's unauthenticated route —
        #: set DYN_ADMIN_TOKEN (or --admin-token) on exposed binds
        self.admin_token = os.environ.get("DYN_ADMIN_TOKEN")
        # overload protection (docs/robustness.md): bounded in-flight work
        # with early 429 rejection beats silent pile-up. Caps read from the
        # layered RuntimeConfig when a runtime is attached, else from env —
        # both spell the knobs DYN_MAX_INFLIGHT / DYN_MAX_QUEUE /
        # DYN_REQUEST_DEADLINE. 0/None disables a cap.
        rcfg = getattr(runtime, "config", None)
        if rcfg is None:
            # runtime-less construction (tests, bench): load the layered
            # config from env so the SAME validation applies — a typo'd or
            # out-of-range knob fails loudly at startup either way
            from dynamo_tpu.runtime.config import RuntimeConfig

            rcfg = RuntimeConfig.load()
        self.max_inflight = rcfg.max_inflight
        self.max_queue = rcfg.max_queue
        #: default end-to-end deadline seconds (None = no deadline) applied
        #: when the client sends no X-Request-Timeout-Ms
        self.default_deadline_s = rcfg.request_deadline
        # multi-tenant QoS (docs/qos.md): tenant identity (API key /
        # x-dynamo-tenant), priority class, per-tenant token-rate +
        # inflight quotas, and the drain-rate estimator that turns the old
        # hardcoded Retry-After: 1 into an observed-backlog estimate
        self.qos = QosConfig.load()
        self.quotas = TenantQuotas(self.qos)
        self._drain_rate = DrainRateEstimator()
        # self-declared tenant ids seen so far; past max_adhoc_tenants new
        # names demote to "default" so a client looping random ids cannot
        # grow buckets/counters/metric labels without bound (docs/qos.md)
        self._adhoc_tenants: set = set()
        self._adhoc_overflow_warned = False
        # session-native serving (docs/sessions.md): conversation state for
        # /v1/responses delta turns, soft worker affinity for the router,
        # idle-session KV parking to G4. DYN_SESSIONS=0 → stateless
        # frontend (previous_response_id turns get the typed 404).
        _scfg = SessionConfig.load()
        self.sessions: Optional[SessionRegistry] = (
            SessionRegistry(_scfg, metrics=self.metrics)
            if _scfg.enabled else None)
        self._session_tasks: set = set()
        # how long a returning turn's dispatch waits for the proactive
        # restore (0 = never wait, pure tokenize-overlap mode)
        try:
            self._session_restore_wait = float(
                os.environ.get("DYN_SESSION_RESTORE_WAIT_S", "1.0"))
        except ValueError:
            self._session_restore_wait = 1.0
        self._draining = False
        self.host = host
        self.port = port
        self._runner: Optional[web.AppRunner] = None
        self._requests = self.metrics.counter(
            "http_requests_total", "HTTP requests by route/model/status"
        )
        self._rejected = self.metrics.counter(
            "http_requests_rejected_total",
            "requests rejected for overload/deadline by route/model/reason"
        )
        self._latency = self.metrics.histogram(
            "http_request_duration_seconds", "Request duration"
        )
        self._ttft = self.metrics.histogram(
            "http_time_to_first_token_seconds", "Time to first streamed token"
        )
        # per-QoS-class TTFT: the autoscaler's SLO-compliance signal
        # (DYN_SLO_<CLASS>_TTFT_P95_MS targets are checked against the
        # interval p95 estimated from these buckets — autoscale/observe.py)
        self._ttft_class = self.metrics.histogram(
            "http_ttft_class_seconds",
            "Time to first streamed token by QoS class")
        self._inflight = self.metrics.gauge("http_inflight_requests", "In-flight requests")
        self._inflight_count = 0
        self._model_inflight: dict[str, int] = {}
        # token counters: the planner's ISL/OSL source (ref: the planner
        # scrapes the frontend's Prometheus — planner/utils/prometheus.py)
        self._prompt_tokens = self.metrics.counter(
            "llm_prompt_tokens_total", "Prompt tokens by model")
        self._completion_tokens = self.metrics.counter(
            "llm_completion_tokens_total", "Completion tokens by model")
        self._finished = self.metrics.counter(
            "llm_requests_finished_total", "Finished LLM requests by model")
        # dynamo_tenant_* families (docs/qos.md): differentiated-service
        # accounting at the edge; the engine-side families (served tokens,
        # queue wait, preemptions) live on the worker's /metrics
        self._tenant_requests = self.metrics.counter(
            "tenant_requests_total", "LLM requests by tenant/class/status")
        self._tenant_rejected = self.metrics.counter(
            "tenant_rejected_total",
            "requests rejected by tenant/class/reason (quotas + shared "
            "admission)")
        self._tenant_tokens = self.metrics.counter(
            "tenant_completion_tokens_total",
            "completion tokens served by tenant/class")
        # latency attribution surfaces (docs/observability.md
        # "Attribution"): rolling SLO error-budget burn per class, the
        # compile-share of breached requests (the autoscaler's compile-
        # cliff-vs-load discriminator), and the fleet breakdown histograms
        # fed from per-request attribution joins (the /v1/attribution
        # route + the optional DYN_ATTR_FEED_S background sampler)
        from dynamo_tpu.autoscale.slo import SloConfig
        from dynamo_tpu.observability.attribution import (BreachCauseEwma,
                                                          SloBurnTracker)

        self.slo = SloConfig.load()
        self._burn = SloBurnTracker(self.slo)
        self._breach_cause = BreachCauseEwma()
        self._burn_gauge = self.metrics.gauge(
            "slo_burn_rate",
            "rolling SLO error-budget burn rate by QoS class "
            "(breach fraction over DYN_SLO_BURN_WINDOW_S / "
            "DYN_SLO_ERROR_BUDGET; 1.0 = budget consumed exactly at the "
            "sustainable rate)")
        self._breach_compile_gauge = self.metrics.gauge(
            "slo_breach_compile_share",
            "EWMA compile share of breached requests' TTFT by class "
            "(from sampled attributions)")
        self._ttft_breakdown = self.metrics.histogram(
            "ttft_breakdown_seconds",
            "per-request TTFT decomposition by attribution phase and "
            "QoS class")
        self._itl_breakdown = self.metrics.histogram(
            "itl_breakdown_seconds",
            "per-request ITL decomposition by attribution phase and "
            "QoS class")
        #: recently finished / recently breached request ids for the
        #: background attribution sampler (newest kept, bounded)
        from collections import deque
        self._attr_done: deque = deque(maxlen=64)
        self._attr_breached: deque = deque(maxlen=64)
        #: request ids already folded into the breakdown histograms —
        #: feeding is once-per-request, or an operator watch-looping
        #: /v1/attribution on one breached id would drag the class's
        #: compile-share EWMA (the autoscaler's breach-cause signal)
        #: toward that single request
        self._attr_fed: deque = deque(maxlen=256)
        self._attr_fed_set: set = set()
        #: classes whose burn gauge has ever been exported — idle ones
        #: keep refreshing to the window-trimmed value (→ 0.0) at scrape
        self._burn_exported: set = set()
        self._attr_task: Optional[asyncio.Task] = None
        #: KV audit plane exposition state (docs/observability.md "KV
        #: audit"): per-model label sets currently on /metrics (key →
        #: True once its departure 0 has been scraped; the series is then
        #: dropped entirely so fleet churn can't grow cardinality without
        #: bound), and one-shot callback registration latches for the
        #: shared-monitor tombstone counter and the cross-model heals
        #: and cycles counters
        self._radix_exported: dict[str, dict] = {}
        self._divergence_exported: dict[str, dict] = {}
        self._age_exported: dict[str, dict] = {}
        self._tombstone_cb_set = False
        self._heals_cb_set = False
        self._cycles_cb_set = False
        # fleet scorecard (docs/observability.md "Fleet scorecard"): joins
        # the instruments above into one falsifiable rollup at
        # /v1/fleet/scorecard, and keeps the hub-saturation window behind
        # dynamo_hub_saturation_ratio{kind} (live headroom vs the measured
        # ceilings in docs/PERF_NOTES.md "Hub ceiling")
        from dynamo_tpu.llm.pipeline import migration_stats
        from dynamo_tpu.observability.scorecard import ScorecardKeeper

        self.scorecard = ScorecardKeeper(
            self, namespace=os.environ.get("DYN_NAMESPACE", "dynamo"))
        self._hub_saturation = self.metrics.gauge(
            "hub_saturation_ratio",
            "live hub op rate over measured ceiling by kind (rpc = "
            "non-stream hub ops/s vs DYN_HUB_CEILING_RPC; blocks = stored "
            "KV blocks/s applied by the radix indexes vs "
            "DYN_HUB_CEILING_BLOCKS)")
        self.metrics.counter(
            "stream_migrations_total",
            "stream migration outcomes (resend / completed / exhausted)"
        ).add_callback(lambda: {
            (("outcome", k),): v for k, v in migration_stats().items()})

    @property
    def tracer(self):
        """Resolved per use: configure_tracer() after service construction
        must not silently split /metrics and /v1/traces from the recorder
        every instrumentation site writes to."""
        return get_tracer()

    # -- overload protection / QoS ------------------------------------------

    def _begin_request(self, model: str, tenant: Optional[str] = None) -> None:
        self._inflight_count += 1
        self._inflight.set(self._inflight_count)
        self._model_inflight[model] = self._model_inflight.get(model, 0) + 1
        if tenant is not None:
            self.quotas.begin(tenant)

    def _end_request(self, model: str, tenant: Optional[str] = None) -> None:
        self._inflight_count -= 1
        self._inflight.set(self._inflight_count)
        n = self._model_inflight.get(model, 1) - 1
        if n <= 0:
            self._model_inflight.pop(model, None)
        else:
            self._model_inflight[model] = n
        if tenant is not None:
            self.quotas.end(tenant)
        # drain-rate sample: every finished request sharpens the
        # Retry-After estimate the next rejection hands out
        self._drain_rate.note()

    def _resolve_qos(self, request: web.Request,
                     has_tools: bool = False) -> tuple[str, str]:
        """(tenant, priority class) for a request (docs/qos.md).

        ``has_tools``: the parsed body carries OpenAI ``tools`` — when the
        operator configured DYN_QOS_TOOL_CLASS (docs/structured.md), tool-
        loop traffic adopts that class unless an explicit
        ``x-dynamo-priority`` header overrides it. This is server policy,
        not a client claim, so the anonymous-escalation clamp below does
        not apply to it.

        Tenant: a configured API key (``Authorization: Bearer``) wins,
        else the ``x-dynamo-tenant`` header, else "default". A tenant
        configured WITH api_keys is a key-protected identity: a bare
        header claiming it is spoofing (it would inherit the tenant's
        class and drain its quotas) and demotes to "default"; unconfigured
        names past the ``max_adhoc_tenants`` cap demote too (bounded
        per-tenant state — a client looping random ids must not be a
        memory/metrics DoS). Priority: the ``x-dynamo-priority`` header,
        else the tenant's configured class, else "standard"; a malformed
        value degrades to the default with a warning (same rule as
        malformed traceparent), and without an API key the header may only
        LOWER the class below the tenant's configured default — an
        anonymous client claiming ``interactive`` would otherwise gain
        weighted-fair priority, preemption of paying tenants' running
        work, and favored routing for free. Escalation above the
        configured default is an authenticated-tenant privilege."""
        tenant = None
        key_authed = False
        auth = request.headers.get("authorization", "")
        if auth.lower().startswith("bearer "):
            tenant = self.qos.tenant_for_api_key(auth[7:].strip())
            key_authed = tenant is not None
        if tenant is None:
            claimed = request.headers.get("x-dynamo-tenant")
            pol = self.qos.tenants.get(claimed) if claimed else None
            if pol is not None and pol.api_keys:
                logger.warning(
                    "x-dynamo-tenant claims key-protected tenant %r "
                    "without its key; using %r", claimed, DEFAULT_TENANT)
            elif claimed and pol is None and claimed != DEFAULT_TENANT:
                # unconfigured self-declared id: admit up to the cap
                if claimed in self._adhoc_tenants \
                        or len(self._adhoc_tenants) \
                        < self.qos.max_adhoc_tenants:
                    self._adhoc_tenants.add(claimed)
                    tenant = claimed
                elif not self._adhoc_overflow_warned:
                    self._adhoc_overflow_warned = True
                    logger.warning(
                        "more than %d distinct x-dynamo-tenant ids seen; "
                        "demoting new ones to %r (DYN_QOS_MAX_TENANTS)",
                        self.qos.max_adhoc_tenants, DEFAULT_TENANT)
            else:
                tenant = claimed
            tenant = tenant or DEFAULT_TENANT
        raw = request.headers.get("x-dynamo-priority")
        base = self.qos.default_priority(tenant)
        # malformed values degrade to the TENANT's class, not the global
        # default — else a key-authed batch tenant's typo escalates it to
        # "standard" past the escalation check below (key_authed skips it)
        cls = normalize_priority(raw, default=base) if raw is not None else base
        if not key_authed and CLASS_RANK[cls] < CLASS_RANK[base]:
            logger.warning(
                "x-dynamo-priority %r escalates above tenant %r's "
                "configured class without an API key; using %r",
                raw, tenant, base)
            cls = base
        if has_tools and self.qos.tool_class and raw is None:
            # tool-loop mapping (operator-configured): agentic round trips
            # block the client per turn, so they class as the operator
            # chose; an explicit header still wins
            cls = self.qos.tool_class
        return tenant, cls

    def _retry_after(self, backlog: int) -> int:
        """Seconds a rejected client should wait, from the observed drain
        rate (completions/s), clamped to [1, 30]; 1 with no signal yet."""
        return self._drain_rate.retry_after_s(backlog)

    # -- latency attribution / SLO burn (docs/observability.md) -----------

    def _note_slo(self, ctx, ttft_s: float) -> None:
        """Fold one first-token latency into the burn-rate ledger and
        refresh the class's gauge; breached requests queue for the
        attribution sampler so the breach CAUSE gets measured too."""
        cls = ctx.priority or "standard"
        self._burn.note(cls, ttft_s)  # O(1); gauges refresh at scrape
        target = self.slo.slo_for(cls).ttft_p95_ms
        if target is not None and ttft_s * 1000.0 > target:
            self._attr_breached.append(ctx.id)

    def _refresh_slo_gauges(self) -> None:
        """Re-export burn + breach-cause for EVERY class ever seen — at
        /metrics scrape time (every consumer reads the scrape: the
        fuser, `dynctl autoscale`, burn alerting), so a class that goes
        idle decays to 0 with its rolling window instead of freezing its
        last (possibly extreme) value on the gauge, and the hot SSE path
        pays only the O(1) ledger note."""
        rates = self._burn.rates()
        for c in self._burn_exported | set(rates):
            self._burn_gauge.set(rates.get(c, 0.0), **{"class": c})
        self._burn_exported |= set(rates)
        # same staleness rule for the compile share — an expired entry
        # reads 0.0, so yesterday's compile cliff can't classify today's
        # pure load breach as compile-dominated and latch the controller
        # into breach_compile_deferred while the SLO burns
        for c, share in self._breach_cause.shares().items():
            self._breach_compile_gauge.set(share, **{"class": c})

    def feed_attribution(self, doc: dict) -> None:
        """Aggregate one attribution document into the fleet breakdown
        histograms (+ the breach-cause EWMA when the request breached its
        class target). Called by the /v1/attribution route and the
        background sampler — both surfaces feed the same series, and a
        request feeds AT MOST ONCE however often it is queried."""
        rid = doc.get("request_id")
        if rid in self._attr_fed_set:
            return
        if len(self._attr_fed) == self._attr_fed.maxlen:
            self._attr_fed_set.discard(self._attr_fed[0])
        self._attr_fed.append(rid)
        self._attr_fed_set.add(rid)
        # scorecard reconciliation: bucket sums vs measured e2e, per doc
        self.scorecard.note_attribution(doc)
        qos = doc.get("qos") or "standard"
        for phase, ms in (doc.get("ttft") or {}).items():
            self._ttft_breakdown.observe(ms / 1000.0, phase=phase, qos=qos)
        for phase, ms in (doc.get("itl") or {}).items():
            self._itl_breakdown.observe(ms / 1000.0, phase=phase, qos=qos)
        target = self.slo.slo_for(qos).ttft_p95_ms
        if target is not None and (doc.get("ttft_ms") or 0.0) > target:
            self._breach_cause.note(doc)
            for cls, share in self._breach_cause.shares().items():
                self._breach_compile_gauge.set(share, **{"class": cls})

    async def _attr_feed_loop(self, interval_s: float) -> None:
        """Background sampler (DYN_ATTR_FEED_S > 0): every interval,
        attribute ONE recent request — breached ones first — and feed the
        histograms. Bounded cost by construction: one fan-out per
        interval, never per request."""
        from dynamo_tpu.observability.attribution import gather_attribution

        while True:
            await asyncio.sleep(interval_s)
            rid = None
            if self._attr_breached:
                rid = self._attr_breached.pop()
            elif self._attr_done:
                rid = self._attr_done.pop()
            if rid is None:
                continue
            try:
                doc = await gather_attribution(rid, runtime=self.runtime)
                if doc is not None:
                    self.feed_attribution(doc)
            except Exception:
                logger.debug("attribution feed failed for %s", rid,
                             exc_info=True)

    def _qos_admission(self, route: str, model: str, tenant: str, cls: str,
                       cost_tokens: float) -> Optional[web.Response]:
        """Per-tenant quota check (BEFORE the shared caps, so one tenant's
        burst is shed as that tenant's 429 instead of consuming the shared
        DYN_MAX_INFLIGHT budget): None = admitted (bucket charged), else
        the 429. Retry-After for rate rejections derives from the tenant's
        own bucket refill time, for inflight rejections from drain."""
        verdict = self.quotas.admit(tenant, cost_tokens)
        if verdict is None:
            return None
        reason, retry_after = verdict
        if reason == "tenant_inflight":
            retry_after = max(retry_after, self._retry_after(1))
        return self._overloaded_response(route, model, reason,
                                         retry_after=retry_after,
                                         tenant=tenant, cls=cls)

    def _admission(self, route: str, model: str,
                   tenant: Optional[str] = None,
                   cls: Optional[str] = None) -> Optional[web.Response]:
        """Admission control: None = admitted, else the rejection response.

        Sheds with OpenAI-style 429 + ``Retry-After`` BEFORE any pipeline
        state is created — under sustained overload the right behavior is
        bounded queues and early rejection, not silent pile-up."""
        reason = None
        if self._draining:
            self._rejected.inc(route=route, model=model, reason="draining")
            self._requests.inc(route=route, model=model, status="503")
            # how long until everything in flight has drained
            ra = self._retry_after(max(1, self._inflight_count))
            return web.json_response(
                error_body("server is draining", "service_unavailable", 503),
                status=503, headers={"Retry-After": str(ra)})
        if self.max_inflight and self._inflight_count >= self.max_inflight:
            reason = "max_inflight"
        elif (self.max_queue
                and self._model_inflight.get(model, 0) >= self.max_queue):
            reason = "max_queue"
        if reason is None:
            return None
        return self._overloaded_response(route, model, reason,
                                         tenant=tenant, cls=cls)

    def _overloaded_response(self, route: str, model: str, reason: str,
                             retry_after: Optional[int] = None,
                             tenant: Optional[str] = None,
                             cls: Optional[str] = None) -> web.Response:
        """The ONE 429 + Retry-After contract — frontend admission sheds,
        tenant-quota sheds, and worker-fleet sheds must stay identical in
        shape so clients back off the same way regardless of which layer
        rejected. Retry-After is an estimate from the observed queue drain
        rate (or the quota's refill time), clamped to [1, 30] s."""
        self._rejected.inc(route=route, model=model, reason=reason)
        self._requests.inc(route=route, model=model, status="429")
        if tenant is not None:
            self._tenant_rejected.inc(route=route, tenant=tenant,
                                      qos=cls or "standard", reason=reason)
        if retry_after is None:
            # one slot must free before this client can be admitted
            backlog = max(1, self._inflight_count - self.max_inflight + 1
                          if self.max_inflight else 1)
            retry_after = self._retry_after(backlog)
        return web.json_response(
            error_body(f"server overloaded ({reason}); retry after the "
                       "indicated delay", "overloaded", 429),
            status=429, headers={"Retry-After": str(int(retry_after))})

    def _deadline_reject(self, route: str, model: str,
                         reason: str = "deadline") -> web.Response:
        """408 response; ``reason`` separates expired-on-arrival
        ("deadline") from admitted work that expired downstream
        ("deadline_inflight") so admission-cap sizing isn't polluted by
        requests that did consume worker capacity."""
        self._rejected.inc(route=route, model=model, reason=reason)
        self._requests.inc(route=route, model=model, status="408")
        return web.json_response(
            error_body("request deadline exceeded", "deadline_exceeded", 408),
            status=408)

    async def drain(self, timeout: float = 30.0) -> None:
        """Graceful drain (SIGTERM path): stop admitting (new work gets 503,
        /health flips to draining so load balancers pull this replica), then
        wait up to ``timeout`` for in-flight streams to finish."""
        self._draining = True
        # flip the discovery doc to ready=false FIRST: peers/dynctl/LBs
        # reading frontends/<ns>/ must stop picking this replica before the
        # in-flight wait begins
        await self._register_frontend()
        deadline = time.monotonic() + timeout
        while self._inflight_count > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        if self._inflight_count:
            logger.warning("drain timeout: %d requests still in flight",
                           self._inflight_count)

    # -- front-door discovery (docs/robustness.md "Front door") ------------

    def _frontend_doc(self) -> dict:
        """The discovery document for this replica. ``ready`` is the
        drain-aware readiness an LB/peer/dynctl keys on — same semantic as
        /health, but readable fleet-wide off one prefix get."""
        host = os.environ.get("DYN_FRONTEND_ADVERTISE") or self.host
        if host in ("0.0.0.0", "::", ""):
            host = "127.0.0.1"
        return {
            "replica": self.replica,
            "url": f"http{'s' if self.tls_cert_path else ''}://{host}:{self.port}",
            "pid": os.getpid(),
            "started": self._started_at,
            "ready": not self._draining,
        }

    async def _register_frontend(self) -> None:
        """Register (or refresh) ``frontends/<ns>/<replica>`` under the
        runtime's primary lease: the key dies with this process (SIGKILL
        included) and is replayed after a hub restart/failover like every
        other registration. No-op without a runtime or replica identity."""
        if self.runtime is None or self.replica is None:
            return
        ns = self.runtime.config.namespace
        key = f"frontends/{ns}/{self.replica}"
        value = json.dumps(self._frontend_doc()).encode()
        try:
            lease = await self.runtime.primary_lease()
            await self.runtime.plane.kv_put(key, value, lease_id=lease)
        except Exception:
            logger.exception("frontend replica registration failed")
            return
        self.runtime.record_registration(key, value)
        self._frontend_key = key

    async def list_frontends(self) -> list[dict]:
        """Live frontend replicas from the discovery prefix (this replica
        included), each doc tagged ``self``. A runtime-less service lists
        only itself — single-process serving has exactly one front door."""
        if self.runtime is None:
            doc = self._frontend_doc()
            doc["self"] = True
            return [doc] if self.replica else []
        ns = self.runtime.config.namespace
        try:
            entries = await self.runtime.plane.kv_get_prefix(
                f"frontends/{ns}/")
        except Exception:
            logger.exception("frontend discovery read failed")
            return []
        out = []
        for key in sorted(entries):
            try:
                doc = json.loads(entries[key])
            except Exception:
                continue
            doc["self"] = key == self._frontend_key
            out.append(doc)
        return out

    def local_kv_digest(self) -> dict:
        """This replica's radix view, digested per model per worker:
        ``{model: {worker_hex: [xor, count]}}`` — the number two replicas
        consuming the same kv_events stream must agree on after settle
        (the PR 15 ledger digest, projected from the router's view)."""
        from dynamo_tpu.observability.kvaudit import u64_hex
        from dynamo_tpu.router.protocols import G4_SOURCE_ID

        models = {}
        for name, sm in self.manager.models.items():
            idx = getattr(sm.router, "indexer", None) if sm.router else None
            tree = getattr(idx, "tree", None)
            if tree is None:
                continue
            per = {}
            for w in tree.worker_counts():
                if w == G4_SOURCE_ID:
                    continue  # G4 sentinel is not a worker
                xor, count = tree.worker_digest(w)
                per[u64_hex(w)] = [xor, count]
            models[name] = per
        return models

    def _record_usage(self, model: str, usage: Optional[dict],
                      ctx: Optional[Context] = None) -> None:
        if not usage:
            return
        self._prompt_tokens.inc(usage.get("prompt_tokens", 0) or 0, model=model)
        self._completion_tokens.inc(usage.get("completion_tokens", 0) or 0,
                                    model=model)
        self._finished.inc(model=model)
        if ctx is not None:
            # candidate for the background attribution sampler
            self._attr_done.append(ctx.id)
        if ctx is not None and ctx.tenant is not None:
            self._tenant_tokens.inc(
                usage.get("completion_tokens", 0) or 0,
                tenant=ctx.tenant, qos=ctx.priority or "standard")

    def build_app(self) -> web.Application:
        app = web.Application(client_max_size=32 * 1024 * 1024)
        app.router.add_post("/v1/chat/completions", self.handle_chat)
        app.router.add_post("/v1/completions", self.handle_completions)
        app.router.add_post("/v1/embeddings", self.handle_embeddings)
        app.router.add_post("/v1/responses", self.handle_responses)
        app.router.add_get("/v1/models", self.handle_models)
        app.router.add_get("/health", self.handle_health)
        app.router.add_get("/live", self.handle_live)
        app.router.add_get("/metrics", self.handle_metrics)
        # stitched request trace (observability spine): spans recorded in
        # this process merged with spans fetched from workers
        app.router.add_get("/v1/traces/{request_id}", self.handle_trace)
        # fleet flight-recorder fan-out (docs/observability.md "Flight
        # recorder"): per-worker step timelines + anomaly summaries
        app.router.add_get("/v1/fleet/steps", self.handle_fleet_steps)
        # fleet scorecard (docs/observability.md "Fleet scorecard"): the
        # joined per-class SLO / attribution / migration / audit /
        # autoscale / hub rollup with its falsifiability checks
        app.router.add_get("/v1/fleet/scorecard", self.handle_scorecard)
        # per-request latency attribution (docs/observability.md
        # "Attribution"): spans ⊕ flight records → named-cause breakdown
        app.router.add_get("/v1/attribution/{request_id}",
                           self.handle_attribution)
        # KV index audit plane (docs/observability.md "KV audit"):
        # per-worker advertised vs resident blocks, divergence, heals
        app.router.add_get("/v1/kv/audit", self.handle_kv_audit)
        # cross-replica convergence surface (docs/robustness.md "Front
        # door"): THIS replica's radix digests, compared by peers'
        # scorecards and the dynctl agreement check
        app.router.add_get("/v1/kv/digest", self.handle_kv_digest)
        # live frontend replicas off the frontends/<ns>/ discovery prefix
        app.router.add_get("/v1/fleet/frontends", self.handle_fleet_frontends)
        # admin: flush every worker's KV cache/prefix state (ref:
        # lib/llm/src/http/service/clear_kv_blocks.rs)
        app.router.add_post("/clear_kv_blocks", self.handle_clear_kv_blocks)
        # live session registry view (docs/sessions.md): the `dynctl
        # sessions` source
        app.router.add_get("/v1/sessions", self.handle_sessions)
        return app

    async def start(self) -> int:
        app = self.build_app()
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        ssl_ctx = None
        if self.tls_cert_path:
            import ssl

            ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ssl_ctx.load_cert_chain(self.tls_cert_path, self.tls_key_path)
        site = web.TCPSite(self._runner, self.host, self.port,
                           ssl_context=ssl_ctx)
        await site.start()
        if self.port == 0:
            self.port = site._server.sockets[0].getsockname()[1]
        logger.info("OpenAI HTTP%s frontend on %s:%d",
                    "S" if ssl_ctx else "", self.host, self.port)
        # optional continuous attribution sampling (off by default: the
        # on-demand /v1/attribution route and dynctl why need no loop)
        feed_s = 0.0
        try:
            feed_s = float(os.environ.get("DYN_ATTR_FEED_S", "0") or 0)
        except ValueError:
            logger.warning("ignoring malformed DYN_ATTR_FEED_S")
        if feed_s > 0:
            self._attr_task = asyncio.get_running_loop().create_task(
                self._attr_feed_loop(feed_s))
        # multi-replica front door: advertise this replica for LBs, peer
        # scorecards, `dynctl frontends`, and client failover lists
        await self._register_frontend()
        # session lifecycle loop (docs/sessions.md): park idle sessions'
        # KV to G4, reap expired ones
        if self.sessions is not None:
            self.sessions.start(self._park_session)
        return self.port

    async def stop(self):
        if self.sessions is not None:
            await self.sessions.stop()
        for t in list(self._session_tasks):
            t.cancel()
        if self._attr_task is not None:
            self._attr_task.cancel()
            try:
                await self._attr_task
            except (asyncio.CancelledError, Exception):
                pass
            self._attr_task = None
        if self._frontend_key is not None and self.runtime is not None:
            # deliberate stop ≠ crash: delete the advert now instead of
            # letting peers see a dead-but-leased replica for a lease TTL
            try:
                await self.runtime.plane.kv_delete(self._frontend_key)
            except Exception:
                pass
            self.runtime.drop_registration(self._frontend_key)
            self._frontend_key = None
        if self._runner:
            await self._runner.cleanup()

    # -- session-native serving (docs/sessions.md) -------------------------

    async def _park_session(self, entry) -> Optional[int]:
        """Reaper callback: park one idle session's KV prefix down the tier
        ladder at its affinity worker. None = worker unreachable (retry
        next sweep); an int is the G4-covered block count."""
        served = self.manager.get(entry.model)
        if served is None or not entry.token_ids:
            return 0
        res = await served.session_op("park", entry.token_ids,
                                      instance_id=entry.worker_id)
        if res is None:
            return None
        return int(res.get("blocks") or 0)

    def _spawn_restore(self, entry, served):
        """Fire the proactive G4→host restore for a returning parked
        session CONCURRENT with tokenization/routing — by the time the
        turn's admission builds its onboard plan, the prefix is host-
        resident and attaches without an object-store round trip. Returns
        the task so the dispatch path can bound-wait on it (see
        :meth:`_await_restore`)."""

        async def _restore():
            try:
                res = await served.session_op("restore", entry.token_ids,
                                              instance_id=entry.worker_id)
                if res is not None and self.sessions is not None:
                    self.sessions.note_restored(
                        entry, int(res.get("blocks") or 0))
            except Exception:
                logger.exception("session restore for %s failed", entry.sid)

        task = asyncio.get_running_loop().create_task(_restore())
        self._session_tasks.add(task)
        task.add_done_callback(self._session_tasks.discard)
        return task

    async def _await_restore(self, ctx) -> None:
        """Bound-wait for an in-flight session restore before dispatching
        the turn. The restore races the pipeline's tokenize→route→admit
        hops; losing that race silently re-prefills the whole history, so
        the dispatch waits up to DYN_SESSION_RESTORE_WAIT_S (default 1s,
        0 = pure overlap mode) — a hung object store degrades to the
        recompute path instead of wedging the turn."""
        task = getattr(ctx, "session_restore", None)
        if task is None or self._session_restore_wait <= 0:
            return
        try:
            # shield: on timeout the restore keeps running (late blocks
            # still help the NEXT turn) — only the wait is abandoned
            await asyncio.wait_for(asyncio.shield(task),
                                   self._session_restore_wait)
        except asyncio.TimeoutError:
            logger.warning("session restore still in flight after %.1fs; "
                           "dispatching without it",
                           self._session_restore_wait)
        except Exception:
            pass  # restore errors are already logged in the task

    def _attach_session(self, ctx, entry, served, kind: str):
        """Stamp the session identity + affinity on the request Context and
        open the turn. The router reads ``session_affinity`` as a logit
        bonus and calls ``on_routed`` back with the serving worker and the
        prompt's token ids (the in-process feedback loop that keeps the
        affinity map and the parkable hash chain current)."""
        ctx.session = entry.sid
        if entry.worker_id is not None:
            ctx.session_affinity = entry.worker_id
        registry = self.sessions

        def on_routed(worker_id, token_ids, _e=entry):
            registry.note_routed(_e, worker_id, token_ids)

        ctx.on_routed = on_routed
        was_parked = registry.begin_turn(entry, kind=kind)
        if was_parked and entry.token_ids:
            ctx.session_restore = self._spawn_restore(entry, served)

    async def handle_sessions(self, request: web.Request) -> web.Response:
        """Live session registry view (docs/sessions.md): ids, turns,
        affinity worker, idle/parked state — the `dynctl sessions` source."""
        if self.sessions is None:
            return web.json_response(
                {"enabled": False, "sessions": [], "count": 0})
        snap = self.sessions.snapshot()
        snap["enabled"] = True
        return web.json_response(snap)

    def _request_context(self, request: web.Request,
                         tenant: Optional[str] = None,
                         priority: Optional[str] = None) -> Context:
        """Per-request Context: honor inbound request-id/traceparent headers
        and bind the contextvar so frontend log lines carry the id. QoS
        identity (tenant + priority class) is stamped here so every
        downstream hop — router bias, engine fair queues, span tags —
        reads one authoritative source."""
        ctx = Context()
        ctx.tenant = tenant
        ctx.priority = priority
        rid = (request.headers.get("x-request-id")
               or request.headers.get("x-dynamo-request-id"))
        if rid:
            ctx.id = rid
        ctx.traceparent = request.headers.get("traceparent")
        ctx.ensure_traceparent()  # synthesize when the client sent none
        # end-to-end deadline: X-Request-Timeout-Ms wins, else the
        # configured default; a malformed header is ignored (same rule as
        # malformed traceparent) rather than failing the request
        timeout_ms: Optional[float] = None
        raw = request.headers.get("x-request-timeout-ms")
        if raw is not None:
            try:
                timeout_ms = float(raw)
            except ValueError:
                logger.warning("ignoring malformed X-Request-Timeout-Ms=%r",
                               raw)
            else:
                # bound to [0, ~31 years]: inf/NaN/1e306 parse as floats but
                # would overflow the remaining-ms wire encoding downstream
                if not 0 <= timeout_ms <= 1e12:
                    logger.warning(
                        "ignoring out-of-range X-Request-Timeout-Ms=%r", raw)
                    timeout_ms = None
        if timeout_ms is None and self.default_deadline_s is not None:
            timeout_ms = self.default_deadline_s * 1000.0
        if timeout_ms is not None:
            ctx.set_timeout_ms(timeout_ms)
        from dynamo_tpu.runtime.context import CURRENT_REQUEST

        CURRENT_REQUEST.set(ctx)
        return ctx

    # -- handlers ----------------------------------------------------------

    async def handle_models(self, request: web.Request) -> web.Response:
        data = [model_entry(m) for m in self.manager.list_models()]
        return web.json_response({"object": "list", "data": data})

    async def handle_clear_kv_blocks(self, request: web.Request) -> web.Response:
        """POST /clear_kv_blocks — fan a cache flush to every worker of
        every served model (ref: clear_kv_blocks.rs:28 — per-worker
        cleared/failed accounting in the response)."""
        if self.admin_token and (request.headers.get("authorization", "")
                                 != f"Bearer {self.admin_token}"):
            return web.json_response({"error": "unauthorized"}, status=401)
        if not self.manager.list_models():
            return web.json_response(
                {"message": "No active worker groups found"})
        cleared, failed = [], []
        for name in self.manager.list_models():
            served = self.manager.get(name)
            try:
                results = await served.clear_kv_blocks()
            except Exception as e:  # noqa: BLE001 — per-model accounting
                failed.append({"name": name, "error": str(e)})
                continue
            for r in results:
                (cleared if r.get("status") == "cleared" else failed).append(
                    {"name": name, **r})
        return web.json_response(
            {"cleared_workers": cleared, "failed_workers": failed})

    async def handle_health(self, request: web.Request) -> web.Response:
        models = self.manager.list_models()
        if self._draining:
            # load balancers must stop sending traffic during SIGTERM drain
            return web.json_response(
                {"status": "draining", "models": models}, status=503)
        status = "healthy" if models else "no_models"
        return web.json_response({"status": status, "models": models})

    async def handle_live(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def handle_metrics(self, request: web.Request) -> web.Response:
        self._refresh_router_metrics()
        self._refresh_slo_gauges()
        await self._refresh_hub_saturation()
        # merged exposition: HTTP registry + the tracer's SLO registry
        # (dynamo_ttft_seconds / dynamo_itl_seconds / dynamo_e2e_seconds /
        # dynamo_phase_seconds{phase=...}) with duplicate headers dropped
        text = render_registries(self.metrics, self.tracer.metrics)
        return web.Response(text=text, content_type="text/plain")

    async def _refresh_hub_saturation(self) -> None:
        """Fold one hub_stats + radix-blocks sample into the saturation
        window and re-export dynamo_hub_saturation_ratio{kind} — at scrape
        time, so the gauge's freshness tracks the scrape interval and the
        hot path pays nothing."""
        hub = None
        plane = self.runtime.plane if self.runtime is not None else None
        if plane is not None and hasattr(plane, "hub_stats"):
            try:
                hub = await plane.hub_stats()
            except Exception:
                hub = None
        self.scorecard.sample_hub(hub)
        for kind, ratio in self.scorecard.saturation.ratios().items():
            if ratio is not None:
                self._hub_saturation.set(ratio, kind=kind)

    async def handle_scorecard(self, request: web.Request) -> web.Response:
        """GET /v1/fleet/scorecard — the joined falsifiable fleet rollup
        (observability/scorecard.py; rendered by ``dynctl fleet``)."""
        return web.json_response(await self.scorecard.document())

    async def handle_trace(self, request: web.Request) -> web.Response:
        """GET /v1/traces/{request_id} — the stitched request trace.

        Merges this process's span buffer with spans fanned out from every
        registered worker tracer (observability/collector.py); the request
        id doubles as the trace id when the client sent no traceparent."""
        rid = request.match_info["request_id"]
        spans = {s.span_id: s.to_dict() for s in self.tracer.spans_for(rid)}
        if self.runtime is not None:
            try:
                for d in await fetch_trace(self.runtime.plane, rid):
                    spans.setdefault(d["span_id"], d)
            except Exception:
                logger.exception("trace fan-out failed; serving local spans")
        if not spans:
            from dynamo_tpu.observability import (trace_sample_rate,
                                                  trace_sampled)

            rate = trace_sample_rate()
            if rate < 1.0 and not trace_sampled(rid, rate):
                # head-sampled out: say so explicitly — an operator
                # debugging a request must be able to tell "not sampled"
                # from "trace expired from the ring buffers". The
                # decision keys on the request id, which IS the trace id
                # unless the client sent its own traceparent — hedge for
                # that case instead of asserting certainty.
                return web.json_response({
                    "request_id": rid, "sampled": False, "spans": [],
                    "reason": (f"request not head-sampled "
                               f"(DYN_TRACE_SAMPLE={rate:g}); raise the "
                               "rate or resend with a sampled trace id. "
                               "If the request carried its own "
                               "traceparent, query by that trace id — "
                               "the sampling decision follows the trace "
                               "id, not the request id"),
                })
            return web.json_response(
                error_body(f"no trace recorded for '{rid}'",
                           "trace_not_found", 404), status=404)
        ordered = sorted(spans.values(), key=lambda d: d.get("start") or 0.0)
        return web.json_response({
            "request_id": rid,
            "trace_id": ordered[0].get("trace_id"),
            "phases": sorted({d.get("name") for d in ordered}),
            "spans": ordered,
        })

    async def handle_fleet_steps(self, request: web.Request) -> web.Response:
        """GET /v1/fleet/steps — the stitched fleet flight view: every
        worker's step summary (and, with ``?n=``, its recent records) fanned
        out over the control plane. Dead/slow workers drop out of the
        response individually (observability/flight.py)."""
        from dynamo_tpu.observability import fetch_fleet_steps

        try:
            n = int(request.query.get("n", "0"))
            since = int(request.query.get("since", "0"))
        except ValueError:
            return web.json_response(
                error_body("query params 'n'/'since' must be integers",
                           "bad_request", 400), status=400)
        workers: dict = {}
        if self.runtime is not None:
            try:
                workers = await fetch_fleet_steps(self.runtime.plane, n=n,
                                                  since=since)
            except Exception:
                logger.exception("fleet step fan-out failed")
        else:
            # runtime-less frontend (tests, single-process serving): the
            # process-local recorders ARE the fleet — with a runtime they
            # arrive through the fan-out instead (never both, or the same
            # ring would show up under two keys)
            from dynamo_tpu.observability.flight import recorders

            for name, rec in recorders().items():
                entry = {"summary": rec.summary()}
                if n > 0 or since > 0:
                    entry["steps"] = rec.snapshot(n if n > 0 else None,
                                                  since=since)
                workers[f"local/{name}"] = entry
        return web.json_response({"workers": workers,
                                  "count": len(workers)})

    async def handle_attribution(self, request: web.Request) -> web.Response:
        """GET /v1/attribution/{request_id} — the critical-path
        decomposition: the request's spans joined with the serving
        workers' StepRecords, every millisecond bucketed into a named
        cause + an explicit unattributed residual
        (docs/observability.md "Attribution").

        Head-sampled-out traces degrade to a flight-only decomposition
        with ``trace_sampled: false`` — never a 404 just because
        DYN_TRACE_SAMPLE was on; 404 only when nothing anywhere mentions
        the id."""
        from dynamo_tpu.observability.attribution import gather_attribution

        rid = request.match_info["request_id"]
        try:
            records = int(request.query.get("records", "2048"))
        except ValueError:
            return web.json_response(
                error_body("query param 'records' must be an integer",
                           "bad_request", 400), status=400)
        try:
            doc = await gather_attribution(rid, runtime=self.runtime,
                                           records=records)
        except Exception:
            logger.exception("attribution join failed")
            return web.json_response(
                error_body("attribution join failed", "internal_error",
                           500), status=500)
        if doc is None:
            from dynamo_tpu.observability import (trace_sample_rate,
                                                  trace_sampled)

            rate = trace_sample_rate()
            reason = "no spans or step records mention this request id"
            if rate < 1.0 and not trace_sampled(rid, rate):
                reason += (f" (and it was not head-sampled at "
                           f"DYN_TRACE_SAMPLE={rate:g}; flight-only "
                           "attribution needs the request inside the "
                           "step ring window)")
            return web.json_response(
                error_body(f"no attribution for '{rid}': {reason}",
                           "attribution_not_found", 404), status=404)
        # every served decomposition also feeds the fleet breakdown
        # histograms — debugging traffic and sampling share one series
        self.feed_attribution(doc)
        return web.json_response(doc)

    async def handle_kv_audit(self, request: web.Request) -> web.Response:
        """GET /v1/kv/audit — the KV index audit plane's live status per
        model (docs/observability.md "KV audit"): per-worker advertised
        vs resident block counts, phantom/missing/dangling divergence
        with age, last heal, suspicion and stale-advert counts. Models
        routed without the event-fed KV indexer (round_robin, approx)
        have nothing to audit and are simply absent."""
        models = {}
        for name, sm in self.manager.models.items():
            auditor = getattr(sm.router, "auditor", None) if sm.router \
                else None
            if auditor is not None:
                models[name] = auditor.status()
        return web.json_response({"models": models, "count": len(models)})

    async def handle_kv_digest(self, request: web.Request) -> web.Response:
        """GET /v1/kv/digest — this replica's per-model per-worker radix
        digests plus the indexer cursors. Replicas feeding off the same
        ``kv_events`` stream must converge to identical digests once the
        stream settles — /v1/fleet/scorecard's ``radix_replica_agreement``
        check fetches this from every live peer and diffs per worker."""
        cursors = {}
        for name, sm in self.manager.models.items():
            idx = getattr(sm.router, "indexer", None) if sm.router else None
            if idx is not None:
                cursors[name] = {
                    "last_seq": getattr(idx, "_last_seq", None),
                    "events_applied": getattr(idx, "events_applied", 0),
                    "gaps_detected": getattr(idx, "gaps_detected", 0),
                    "resyncs_requested": getattr(idx, "resyncs_requested", 0),
                }
        return web.json_response({
            "replica": self.replica,
            "models": self.local_kv_digest(),
            "cursors": cursors,
        })

    async def handle_fleet_frontends(self, request: web.Request) -> web.Response:
        """GET /v1/fleet/frontends — live frontend replicas with drain-aware
        readiness (the worker-side analog is /v1/fleet/steps; this is the
        front door's census, rendered by ``dynctl frontends``)."""
        frontends = await self.list_frontends()
        return web.json_response({
            "frontends": frontends,
            "count": len(frontends),
            "ready": sum(1 for f in frontends if f.get("ready", True)),
        })

    @staticmethod
    def _decay_departed(gauge, exported: dict, current: set,
                        labelize) -> None:
        """Label-churn hygiene for per-worker gauges: a departed label
        set gets ONE 0-valued scrape (so dashboards see the decay, not a
        frozen last value), then the series leaves /metrics entirely —
        under autoscaler churn every restart mints a new lease hex, and
        an ever-growing set of 0-valued series is an unbounded scrape."""
        for key in [k for k in exported if k not in current]:
            if exported[key]:
                gauge.remove(**labelize(key))
                del exported[key]
            else:
                gauge.set(0, **labelize(key))
                exported[key] = True
        for key in current:
            exported[key] = False

    def _refresh_router_metrics(self) -> None:
        """Snapshot per-model KV-router stream health into gauges at scrape
        time (ref role: the reference's router metrics aggregation). A
        nonzero gaps/resyncs rate is the operator's signal that the event
        stream is outrunning its consumers (ring cap / hub sizing)."""
        from dynamo_tpu.router.indexer import KvIndexer
        from dynamo_tpu.observability.kvaudit import u64_hex
        from dynamo_tpu.router.protocols import G4_SOURCE_ID

        for name, sm in self.manager.models.items():
            # tombstone-rejected late kv_metrics (runtime/worker_monitor):
            # the shared monitor serves every model AND every router mode
            # (round_robin fleets tombstone too) — export once, before
            # the KV-indexer gate below
            if sm.monitor is not None and not self._tombstone_cb_set:
                self._tombstone_cb_set = True
                monitor = sm.monitor
                self.metrics.counter(
                    "kv_events_tombstoned_total",
                    "late kv_metrics publishes rejected by a dead-worker "
                    "tombstone (rate-limited WARN; a steady rate means "
                    "something keeps publishing for a purged "
                    "worker)").add_callback(
                    lambda: {None: monitor.tombstoned_total})
            idx = getattr(sm.router, "indexer", None) if sm.router else None
            if not isinstance(idx, KvIndexer):
                continue
            for field in ("events_applied", "gaps_detected",
                          "resyncs_requested", "snapshots_written"):
                self.metrics.gauge(
                    f"kv_router_{field}",
                    "KV event stream health").set(getattr(idx, field),
                                                  model=name)
            self.metrics.gauge(
                "kv_router_orphan_events",
                "stored events dropped for unknown parents").set(
                    idx.tree.orphan_events, model=name)
            # radix shape (docs/observability.md "KV audit"): the index's
            # size was invisible — per-worker advertised block counts,
            # the worker census, and the G4 sentinel's announced prefix
            # depth, all O(workers) off the tree's inline digests
            counts = idx.tree.worker_counts()
            g4_blocks = counts.pop(G4_SOURCE_ID, 0)
            blocks_g = self.metrics.gauge(
                "radix_blocks",
                "blocks the KV radix index advertises per worker")
            self._decay_departed(
                blocks_g, self._radix_exported.setdefault(name, {}),
                {u64_hex(w) for w in counts},
                lambda whex: {"model": name, "worker": whex})
            for w, c in counts.items():
                blocks_g.set(c, model=name, worker=u64_hex(w))
            self.metrics.gauge(
                "radix_workers",
                "workers with at least one advertised block in the KV "
                "radix index").set(len(counts), model=name)
            self.metrics.gauge(
                "radix_g4_blocks",
                "G4 object-store prefix blocks announced under the "
                "sentinel source").set(g4_blocks, model=name)
            # audit plane results (kvaudit.KvAuditor)
            auditor = getattr(sm.router, "auditor", None)
            if auditor is not None:
                div_g = self.metrics.gauge(
                    "radix_divergence_blocks",
                    "radix↔residency divergent blocks per worker by kind "
                    "(phantom = advertised not resident, missing = "
                    "resident not advertised, dangling = resident but "
                    "not re-announceable)")
                div_keys = set()
                for (w, kind), n in auditor.divergence_blocks().items():
                    div_g.set(n, model=name, worker=u64_hex(w), kind=kind)
                    div_keys.add((u64_hex(w), kind))
                self._decay_departed(
                    div_g, self._divergence_exported.setdefault(name, {}),
                    div_keys,
                    lambda k: {"model": name, "worker": k[0], "kind": k[1]})
                age_g = self.metrics.gauge(
                    "radix_divergence_age_seconds",
                    "seconds since unhealed divergence was first "
                    "detected, per worker (0 = clean)")
                import time as _time

                now = _time.time()
                age_keys = set()
                for wid, st in auditor.worker_state.items():
                    since = st.get("diverged_since")
                    whex = u64_hex(wid)
                    age_g.set(round(now - since, 3) if since else 0.0,
                              model=name, worker=whex)
                    age_keys.add(whex)
                self._decay_departed(
                    age_g, self._age_exported.setdefault(name, {}),
                    age_keys,
                    lambda whex: {"model": name, "worker": whex})
                heals = self.metrics.counter(
                    "kv_audit_heals_total",
                    "audit-triggered resync heals by cause (phantom "
                    "purges the worker's radix entries first; missing "
                    "replays idempotent upserts)")
                if not self._heals_cb_set:
                    self._heals_cb_set = True
                    mgr2 = self.manager  # late-bound over all models
                    # counters must be MONOTONIC: a model teardown (last
                    # worker left) destroys its auditor, so a live-sum
                    # would decrease and Prometheus rate() would read the
                    # drop as a process restart. Fold each auditor's last
                    # seen counts into a retained baseline when it
                    # disappears (or restarts at lower counts).
                    last: dict = {}  # model -> last seen heals_total
                    base: dict = {}  # cause -> retired heals

                    def _heals():
                        live = set()
                        for mname, sm2 in mgr2.models.items():
                            a = getattr(sm2.router, "auditor", None) \
                                if sm2.router else None
                            if a is None:
                                continue
                            live.add(mname)
                            cur = dict(a.heals_total)
                            prev = last.get(mname)
                            if prev and any(cur.get(c, 0) < n
                                            for c, n in prev.items()):
                                for c, n in prev.items():  # new auditor
                                    base[c] = base.get(c, 0) + n
                            last[mname] = cur
                        for mname in [m for m in last if m not in live]:
                            for c, n in last.pop(mname).items():
                                base[c] = base.get(c, 0) + n
                        out: dict = {}
                        for src in [base] + [last[m] for m in last]:
                            for cause, n in src.items():
                                key = (("cause", cause),)
                                out[key] = out.get(key, 0) + n
                        return out

                    heals.add_callback(_heals)
                cycles = self.metrics.counter(
                    "kv_audit_cycles_total", "audit cycles completed")
                if not self._cycles_cb_set:
                    self._cycles_cb_set = True
                    mgr = self.manager  # late-bound over all models
                    # same monotonicity hazard as _heals above: a model
                    # teardown destroys its auditor and a recreated one
                    # restarts cycles at 0 — fold retired counts into a
                    # per-model baseline so the counter never decreases
                    cyc_last: dict = {}  # model -> last seen cycles
                    cyc_base: dict = {}  # model -> retired cycles

                    def _cycles():
                        out: dict = {}
                        live = set()
                        for mname, sm2 in mgr.models.items():
                            a = getattr(sm2.router, "auditor", None) \
                                if sm2.router else None
                            if a is None:
                                continue
                            live.add(mname)
                            if a.cycles < cyc_last.get(mname, 0):
                                cyc_base[mname] = cyc_base.get(mname, 0) \
                                    + cyc_last[mname]
                            cyc_last[mname] = a.cycles
                        for mname in [m for m in cyc_last if m not in live]:
                            cyc_base[mname] = cyc_base.get(mname, 0) \
                                + cyc_last.pop(mname)
                        for mname in set(cyc_last) | set(cyc_base):
                            out[(("model", mname),)] = \
                                cyc_base.get(mname, 0) + cyc_last.get(mname, 0)
                        return out

                    cycles.add_callback(_cycles)

    async def handle_embeddings(self, request: web.Request) -> web.Response:
        """OpenAI embeddings (ref: openai.rs:714): tokenize each input via
        the model's tokenizer, mean-pool on a worker, return vectors."""
        t0 = time.perf_counter()
        try:
            body = await request.json()
            if not isinstance(body, dict):
                raise ValueError
        except Exception:
            self._requests.inc(route="embeddings", model="unknown", status="400")
            return web.json_response(error_body("invalid JSON body"), status=400)
        model = body.get("model")
        served = self.manager.get(model) if isinstance(model, str) else None
        if served is None:
            self._requests.inc(route="embeddings", model=str(model), status="404")
            return web.json_response(
                error_body(f"model '{model}' not found", "model_not_found", 404),
                status=404)
        ctx = self._request_context(request)
        raw = body.get("input")
        if isinstance(raw, str):
            inputs = [raw]
        elif isinstance(raw, list) and raw and all(isinstance(t, int) for t in raw):
            inputs = [raw]  # one pre-tokenized input
        elif isinstance(raw, list):
            inputs = raw
        else:
            self._requests.inc(route="embeddings", model=model, status="400")
            return web.json_response(
                error_body("'input' must be a string, array of strings, or "
                           "array of token arrays"), status=400)
        tk = served.pipeline.tokenizer
        token_lists, n_tokens = [], 0
        for item in inputs:
            if isinstance(item, str):
                ids = tk.encode(item)
            elif isinstance(item, list) and all(isinstance(t, int) for t in item):
                ids = list(item)
            else:
                self._requests.inc(route="embeddings", model=model, status="400")
                return web.json_response(
                    error_body("each input must be a string or token array"),
                    status=400)
            if not ids:
                ids = [0]
            token_lists.append(ids)
            n_tokens += len(ids)
        # bound inputs at the HTTP edge too (dense S×S attention worker-side);
        # the worker enforces its own batch budget as the authority
        limit = served.card.context_length
        if any(len(t) > limit for t in token_lists):
            self._requests.inc(route="embeddings", model=model, status="400")
            return web.json_response(
                error_body(f"embedding input exceeds context length {limit}"),
                status=400)
        if len(token_lists) > 256:
            self._requests.inc(route="embeddings", model=model, status="400")
            return web.json_response(
                error_body("at most 256 inputs per embeddings request"),
                status=400)
        # root span so the worker's embed spans have a recorded parent
        with self.tracer.span(
                "http.request", ctx, service="frontend",
                adopt_wire_span=ctx.traceparent_synthesized,
                route="embeddings", model=model):
            try:
                vecs = await served.embed(token_lists, ctx=ctx)
            except ValueError as e:
                self._requests.inc(route="embeddings", model=model, status="400")
                return web.json_response(error_body(str(e)), status=400)
            except NoRespondersError:
                self._requests.inc(route="embeddings", model=model, status="503")
                return web.json_response(
                    error_body("no workers available", "service_unavailable", 503),
                    status=503)
        self._requests.inc(route="embeddings", model=model, status="200")
        self._latency.observe(time.perf_counter() - t0, route="embeddings")
        return web.json_response({
            "object": "list",
            "model": model,
            "data": [{"object": "embedding", "index": i, "embedding": v}
                     for i, v in enumerate(vecs)],
            "usage": {"prompt_tokens": n_tokens, "total_tokens": n_tokens},
        })

    async def handle_responses(self, request: web.Request) -> web.StreamResponse:
        """OpenAI Responses API (ref: openai.rs:1005): ``input`` +
        ``instructions`` lower onto the chat pipeline; streaming emits
        typed ``response.*`` SSE events."""
        t0 = time.perf_counter()
        try:
            body = await request.json()
            if not isinstance(body, dict):
                raise ValueError
        except Exception:
            self._requests.inc(route="responses", model="unknown", status="400")
            return web.json_response(error_body("invalid JSON body"), status=400)
        try:
            parsed = parse_responses_request(body)
        except RequestError as e:
            self._requests.inc(route="responses", model=str(body.get("model")),
                               status="400")
            return web.json_response(error_body(str(e)), status=400)
        served = self.manager.get(parsed.model)
        if served is None:
            self._requests.inc(route="responses", model=parsed.model, status="404")
            return web.json_response(
                error_body(f"model '{parsed.model}' not found",
                           "model_not_found", 404), status=404)

        tenant, qos_class = self._resolve_qos(request,
                                              has_tools=bool(parsed.tools))

        # session resolution (docs/sessions.md) BEFORE admission: an
        # unknown previous_response_id is the caller's typed 404 — it must
        # not charge quota, and it must NEVER silently fall back to
        # serving the delta as if it were the full conversation
        rid = gen_request_id("resp")
        session_entry = None
        turn_kind = "full"
        delta_chars_saved = 0
        if parsed.previous_response_id is not None:
            if self.sessions is None:
                self._requests.inc(route="responses", model=parsed.model,
                                   status="404")
                return web.json_response(
                    error_body("previous_response_id cannot resolve: the "
                               "session registry is disabled on this "
                               "frontend (DYN_SESSIONS=0) — resend the "
                               "full conversation",
                               "previous_response_not_found", 404),
                    status=404)
            try:
                session_entry = self.sessions.resolve_response(
                    parsed.previous_response_id)
            except UnknownResponseError as e:
                self._requests.inc(route="responses", model=parsed.model,
                                   status="404")
                return web.json_response(
                    error_body(str(e), "previous_response_not_found", 404),
                    status=404)
            # delta turn: the client shipped only the new input items —
            # reconstruct the full prompt from the server-held history
            if session_entry.messages:
                delta_chars_saved = sum(
                    len(str(m.get("content") or ""))
                    for m in session_entry.messages)
                parsed.messages = (list(session_entry.messages)
                                   + list(parsed.messages))
            turn_kind = "delta"
        elif self.sessions is not None:
            sid = request.headers.get("x-dynamo-session")
            if not sid and parsed.raw.get("store", True) is not False:
                # anonymous first turn, store=true (the OpenAI default):
                # the response id we are about to mint is itself a resume
                # point, so the session is keyed by it — a later delta
                # turn resolves rid without any header
                sid = rid
            if sid:
                session_entry = self.sessions.get_or_create(
                    sid, parsed.model, tenant=tenant)
                if session_entry is not None and session_entry.turns == 0:
                    turn_kind = "first"

        cost = parsed.stop.max_tokens or self.qos.default_cost
        rejection = self._qos_admission(
            "responses", parsed.model, tenant, qos_class, cost)
        if rejection is not None:
            return rejection
        rejection = self._admission("responses", parsed.model,
                                    tenant=tenant, cls=qos_class)
        if rejection is not None:
            # the quota charge above bought no service — refund it, or
            # retries through an overloaded frontend drain the bucket
            self.quotas.refund(tenant, cost)
            return rejection
        ctx = self._request_context(request, tenant=tenant,
                                    priority=qos_class)
        if ctx.expired:
            self.quotas.refund(tenant, cost)
            return self._deadline_reject("responses", parsed.model)
        created = int(time.time())
        if session_entry is not None:
            self._attach_session(ctx, session_entry, served, turn_kind)
        self._begin_request(parsed.model, tenant)
        self._tenant_requests.inc(route="responses", tenant=tenant,
                                  qos=qos_class)
        # root span (same contract as _handle_llm): downstream phases must
        # have a recorded parent or the trace renders as an orphan forest
        with self.tracer.span(
                "http.request", ctx, service="frontend",
                adopt_wire_span=ctx.traceparent_synthesized,
                route="responses", model=parsed.model,
                tenant=tenant, qos=qos_class):
            return await self._handle_responses_inner(
                request, served, parsed, ctx, rid, created, t0,
                session_entry=session_entry,
                delta_chars_saved=delta_chars_saved)

    async def _handle_responses_inner(self, request, served, parsed, ctx,
                                      rid, created, t0, session_entry=None,
                                      delta_chars_saved=0
                                      ) -> web.StreamResponse:
        turn_closed = session_entry is None
        try:
            await self._await_restore(ctx)
            stream = served.pipeline.generate(parsed, ctx)
            if parsed.stream:
                turn_closed = True  # the SSE path owns turn completion
                return await self._stream_responses_sse(
                    request, stream, ctx, parsed.model, rid, created, t0,
                    parsed=parsed, session_entry=session_entry,
                    delta_chars_saved=delta_chars_saved)
            try:
                result = await aggregate_chat_stream(stream)
            except DeadlineExceededError:
                return self._deadline_reject("responses", parsed.model,
                                             reason="deadline_inflight")
            except OverloadedError:
                return self._overloaded_response(
                    "responses", parsed.model, "worker_overloaded")
            except NoRespondersError:
                self._requests.inc(route="responses", model=parsed.model,
                                   status="503")
                return web.json_response(
                    error_body("no workers available", "service_unavailable",
                               503), status=503)
            except StreamError as e:
                # same mapping as the chat route: a typed invalid_request
                # from the worker (unsatisfiable constraint) is the
                # caller's 400; other stream failures are a clean 502
                status = 400 if e.code == "invalid_request" else 502
                self._requests.inc(route="responses", model=parsed.model,
                                   status=str(status))
                return web.json_response(
                    error_body(str(e),
                               "invalid_request_error" if status == 400
                               else "upstream_error", status),
                    status=status)
            except (ValueError, RuntimeError) as e:
                self._requests.inc(route="responses", model=parsed.model,
                                   status="400")
                return web.json_response(error_body(str(e)), status=400)
            self._record_usage(parsed.model, result.get("usage"), ctx=ctx)
            choice = result["choices"][0]
            text = choice["message"].get("content") or ""
            # responses-API status: max_output_tokens truncation reports
            # "incomplete", everything else "completed"
            status_word = ("incomplete" if choice.get("finish_reason") == "length"
                           else "completed")
            if session_entry is not None:
                # the turn's FULL history + reply under the new response
                # id: the next delta turn resolves rid and prepends this
                self.sessions.complete_turn(
                    session_entry, rid, parsed.messages, text,
                    delta_chars_saved=delta_chars_saved)
                turn_closed = True
            self._requests.inc(route="responses", model=parsed.model, status="200")
            self._latency.observe(time.perf_counter() - t0, route="responses")
            out = response_object(rid, parsed.model, created, text, status_word,
                                  result.get("usage"))
            if status_word == "incomplete":
                out["incomplete_details"] = {"reason": "max_output_tokens"}
            return web.json_response(out, headers={"x-request-id": ctx.id})
        finally:
            if not turn_closed and self.sessions is not None:
                # failed turn: drop the in-flight mark, store nothing —
                # the previous response id stays the resume point
                self.sessions.abort_turn(session_entry)
            self._end_request(parsed.model, ctx.tenant)

    async def _stream_responses_sse(self, request, stream, ctx, model,
                                    rid, created, t0, parsed=None,
                                    session_entry=None,
                                    delta_chars_saved=0) -> web.StreamResponse:
        resp = web.StreamResponse(
            status=200,
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache", "x-request-id": ctx.id})
        await resp.prepare(request)

        def record(event: str, payload: dict) -> bytes:
            return f"event: {event}\ndata: {json.dumps(payload)}\n\n".encode()

        async def emit(event: str, payload: dict):
            await resp.write(record(event, payload))

        status = "200"
        parts: list[str] = []
        usage = None
        # same TTFT/ITL phase recording as _stream_sse, keyed on output
        # text deltas — responses traffic must feed the same SLO series
        timing = _StreamTiming(self, "responses", t0)
        try:
            await emit("response.created", {
                "type": "response.created",
                "response": response_object(rid, model, created, "",
                                            "in_progress")})
            finish = None
            stop = False
            async for items in _batched(stream):
                # one transport write per batch (same coalescing as
                # _stream_sse — typed events re-split client-side unchanged)
                buf = bytearray()
                for wire in items:
                    ann = Annotated.from_wire(wire)
                    if ann.is_error():
                        buf += record("response.failed", {
                            "type": "response.failed",
                            "response": response_object(
                                rid, model, created, "".join(parts),
                                "failed")})
                        status = "500"
                        stop = True
                        break
                    if ann.event is not None:
                        continue
                    chunk = ann.data
                    if chunk.get("usage"):
                        usage = chunk["usage"]
                        self._record_usage(model, usage, ctx=ctx)
                    for ch in chunk.get("choices", []):
                        delta = (ch.get("delta") or {}).get("content")
                        finish = ch.get("finish_reason") or finish
                        if delta:
                            if timing.tick():
                                dt = time.perf_counter() - t0
                                self._ttft.observe(dt, route="responses")
                                self._ttft_class.observe(
                                    dt, qos=ctx.priority or "standard")
                                self._note_slo(ctx, dt)
                            parts.append(delta)
                            buf += record("response.output_text.delta", {
                                "type": "response.output_text.delta",
                                "item_id": response_msg_id(rid),
                                "output_index": 0, "content_index": 0,
                                "delta": delta})
                if buf:
                    await resp.write(bytes(buf))
                if stop:
                    break
            if status == "200":
                text = "".join(parts)
                await emit("response.output_text.done", {
                    "type": "response.output_text.done",
                    "item_id": response_msg_id(rid),
                    "output_index": 0, "content_index": 0, "text": text})
                # max_output_tokens truncation ends the stream with
                # response.incomplete (OpenAI semantics); clean EOS/stop
                # ends with response.completed
                word = "incomplete" if finish == "length" else "completed"
                final = response_object(rid, model, created, text, word, usage)
                if word == "incomplete":
                    final["incomplete_details"] = {
                        "reason": "max_output_tokens"}
                await emit(f"response.{word}",
                           {"type": f"response.{word}", "response": final})
        except (ConnectionResetError, asyncio.CancelledError):
            ctx.cancel()
            status = "499"
            raise
        except DeadlineExceededError:
            await emit("response.failed", {
                "type": "response.failed",
                "response": response_object(rid, model, created,
                                            "".join(parts), "failed")})
            status = "408"
        except OverloadedError:
            await emit("response.failed", {
                "type": "response.failed",
                "response": response_object(rid, model, created,
                                            "".join(parts), "failed")})
            self._rejected.inc(route="responses", model=model,
                               reason="worker_overloaded")
            status = "429"
        except NoRespondersError:
            await emit("response.failed", {
                "type": "response.failed",
                "response": response_object(rid, model, created,
                                            "".join(parts), "failed")})
            status = "503"
        except Exception:
            logger.exception("responses stream failed")
            await emit("response.failed", {
                "type": "response.failed",
                "response": response_object(rid, model, created,
                                            "".join(parts), "failed")})
            status = "500"
        finally:
            if session_entry is not None and self.sessions is not None:
                if status == "200" and parsed is not None:
                    self.sessions.complete_turn(
                        session_entry, rid, parsed.messages, "".join(parts),
                        delta_chars_saved=delta_chars_saved)
                else:
                    # broken/failed stream: the reply may be truncated —
                    # don't store it; the previous id stays the resume point
                    self.sessions.abort_turn(session_entry)
            self._requests.inc(route="responses", model=model, status=status)
            self._latency.observe(time.perf_counter() - t0, route="responses")
            timing.finish(ctx)
        await resp.write_eof()
        return resp

    async def handle_chat(self, request: web.Request) -> web.StreamResponse:
        return await self._handle_llm(request, chat=True)

    async def handle_completions(self, request: web.Request) -> web.StreamResponse:
        return await self._handle_llm(request, chat=False)

    async def _handle_llm(self, request: web.Request, chat: bool) -> web.StreamResponse:
        route = "chat" if chat else "completions"
        t0 = time.perf_counter()
        try:
            body = await request.json()
            if not isinstance(body, dict):
                raise ValueError
        except Exception:
            self._requests.inc(route=route, model="unknown", status="400")
            return web.json_response(error_body("invalid JSON body"), status=400)
        try:
            parsed = parse_chat_request(body) if chat else parse_completion_request(body)
        except RequestError as e:
            self._requests.inc(route=route, model=str(body.get("model")), status="400")
            return web.json_response(error_body(str(e)), status=400)

        served = self.manager.get(parsed.model)
        if served is None:
            self._requests.inc(route=route, model=parsed.model, status="404")
            return web.json_response(
                error_body(f"model '{parsed.model}' not found", "model_not_found", 404),
                status=404,
            )

        tenant, qos_class = self._resolve_qos(request,
                                              has_tools=bool(parsed.tools))
        cost = parsed.stop.max_tokens or self.qos.default_cost
        rejection = self._qos_admission(
            route, parsed.model, tenant, qos_class, cost)
        if rejection is not None:
            return rejection
        rejection = self._admission(route, parsed.model,
                                    tenant=tenant, cls=qos_class)
        if rejection is not None:
            # the quota charge above bought no service — refund it, or
            # retries through an overloaded frontend drain the bucket
            self.quotas.refund(tenant, cost)
            return rejection
        ctx = self._request_context(request, tenant=tenant,
                                    priority=qos_class)
        if ctx.expired:
            # expired on arrival (e.g. X-Request-Timeout-Ms: 0, or queued
            # behind a slow LB): reject with 408 before any worker sees it
            self.quotas.refund(tenant, cost)
            return self._deadline_reject(route, parsed.model)
        # x-dynamo-session on chat/completions (docs/sessions.md): no
        # server-held conversation state (the client ships full prompts),
        # but the session still gets router affinity, idle parking, and a
        # proactive restore when it returns to a parked prefix
        if self.sessions is not None:
            sid = request.headers.get("x-dynamo-session")
            if sid:
                entry = self.sessions.get_or_create(sid, parsed.model,
                                                    tenant=tenant)
                if entry is not None:
                    ctx.session = entry.sid
                    if entry.worker_id is not None:
                        ctx.session_affinity = entry.worker_id
                    registry = self.sessions

                    def on_routed(worker_id, token_ids, _e=entry):
                        registry.note_routed(_e, worker_id, token_ids)

                    ctx.on_routed = on_routed
                    if registry.touch_turn(entry) and entry.token_ids:
                        ctx.session_restore = self._spawn_restore(
                            entry, served)
        self._begin_request(parsed.model, tenant)
        self._tenant_requests.inc(route=route, tenant=tenant, qos=qos_class)
        # root span: every downstream phase (tokenize, route, worker,
        # engine, TTFT/ITL) parents under it; duration feeds
        # dynamo_e2e_seconds via the tracer's SLO registry. When WE
        # synthesized the traceparent the root adopts its span id (no
        # phantom parent); a client-sent traceparent stays the parent.
        with self.tracer.span(
                "http.request", ctx, service="frontend",
                adopt_wire_span=ctx.traceparent_synthesized,
                route=route, model=parsed.model,
                tenant=tenant, qos=qos_class) as root:
            try:
                await self._await_restore(ctx)
                stream = served.pipeline.generate(parsed, ctx)
                if parsed.stream:
                    return await self._stream_sse(
                        request, stream, ctx, route, parsed.model, t0,
                        keep_usage=parsed.stream_usage)
                try:
                    agg = aggregate_chat_stream(stream) if chat else aggregate_completion_stream(stream)
                    result = await agg
                    self._record_usage(parsed.model, result.get("usage"),
                                       ctx=ctx)
                except DeadlineExceededError:
                    root.set(status_code=408)
                    return self._deadline_reject(route, parsed.model,
                                                 reason="deadline_inflight")
                except OverloadedError:
                    # the WORKER fleet shed the request (typed terminal
                    # error): same 429 + Retry-After contract as frontend
                    # admission, so clients back off identically
                    root.set(status_code=429)
                    return self._overloaded_response(
                        route, parsed.model, "worker_overloaded")
                except NoRespondersError:
                    root.set(status_code=503)
                    self._requests.inc(route=route, model=parsed.model, status="503")
                    return web.json_response(
                        error_body("no workers available", "service_unavailable", 503), status=503
                    )
                except StreamError as e:
                    # worker-side typed failure that exhausted migration:
                    # invalid_request (e.g. an unsatisfiable constraint —
                    # docs/structured.md) is the CALLER's error → 400;
                    # anything else is an upstream failure → clean 502
                    # JSON instead of aiohttp's bare 500
                    status = 400 if e.code == "invalid_request" else 502
                    root.set(status_code=status)
                    self._requests.inc(route=route, model=parsed.model,
                                       status=str(status))
                    return web.json_response(
                        error_body(str(e),
                                   "invalid_request_error"
                                   if status == 400 else "upstream_error",
                                   status),
                        status=status)
                except (ValueError, RuntimeError) as e:
                    root.set(status_code=400)
                    self._requests.inc(route=route, model=parsed.model, status="400")
                    return web.json_response(error_body(str(e)), status=400)
                self._requests.inc(route=route, model=parsed.model, status="200")
                self._latency.observe(time.perf_counter() - t0, route=route)
                return web.json_response(result, headers={"x-request-id": ctx.id})
            finally:
                self._end_request(parsed.model, tenant)

    async def _stream_sse(
        self, request: web.Request, stream, ctx: Context, route: str,
        model: str, t0: float, keep_usage: bool = True
    ) -> web.StreamResponse:
        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "x-request-id": ctx.id,
            },
        )
        await resp.prepare(request)
        status = "200"
        timing = _StreamTiming(self, route, t0)
        try:
            stop = False
            async for items in _batched(stream):
                # one transport write per batch: chunks that queued up while
                # the previous write was in flight coalesce (still one SSE
                # `data:` record per chunk — clients re-split unchanged)
                buf = bytearray()
                for wire in items:
                    ann = Annotated.from_wire(wire)
                    if ann.is_error():
                        payload = {"error": {"message": "; ".join(ann.comment or []), "type": "engine_error"}}
                        buf += f"data: {json.dumps(payload)}\n\n".encode()
                        status = "500"
                        stop = True
                        break
                    if ann.event is not None:
                        buf += f"event: {ann.event}\ndata: {json.dumps(ann.data)}\n\n".encode()
                        continue
                    if timing.tick():
                        dt = time.perf_counter() - t0
                        self._ttft.observe(dt, route=route)
                        self._ttft_class.observe(
                            dt, qos=ctx.priority or "standard")
                        self._note_slo(ctx, dt)
                    data = ann.data
                    if isinstance(data, dict) and "usage" in data:
                        # the pipeline always attaches final-chunk usage for
                        # metrics; only clients that asked get it on the wire
                        self._record_usage(model, data.get("usage"), ctx=ctx)
                        if not keep_usage:
                            data = {k: v for k, v in data.items() if k != "usage"}
                    buf += f"data: {json.dumps(data)}\n\n".encode()
                if buf:
                    await resp.write(bytes(buf))
                if stop:
                    break
            await resp.write(b"data: [DONE]\n\n")
        except (ConnectionResetError, asyncio.CancelledError):
            # client went away: propagate cancellation to the worker
            ctx.cancel()
            status = "499"
            raise
        except DeadlineExceededError:
            await resp.write(
                f"data: {json.dumps(error_body('request deadline exceeded', 'deadline_exceeded', 408))}\n\n".encode()
            )
            status = "408"
        except OverloadedError:
            # fleet shed after the SSE response opened: can't change the
            # HTTP status, but the in-band error keeps the overloaded type
            # so clients back off like the non-stream 429 path
            await resp.write(
                f"data: {json.dumps(error_body('worker fleet overloaded; retry later', 'overloaded', 429))}\n\n".encode()
            )
            self._rejected.inc(route=route, model=model,
                               reason="worker_overloaded")
            status = "429"
        except NoRespondersError:
            await resp.write(
                f"data: {json.dumps(error_body('no workers available', 'service_unavailable', 503))}\n\n".encode()
            )
            status = "503"
        except Exception as e:
            logger.exception("stream failed")
            await resp.write(
                f"data: {json.dumps(error_body(f'stream error: {e!r}', 'internal_error', 500))}\n\n".encode()
            )
            status = "500"
        finally:
            self._requests.inc(route=route, model=model, status=status)
            self._latency.observe(time.perf_counter() - t0, route=route)
            timing.finish(ctx)
        await resp.write_eof()
        return resp
