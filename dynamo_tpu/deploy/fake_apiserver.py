"""In-repo Kubernetes API server speaking the real REST contract.

The envtest analog: the reference's Go operator is developed against
controller-runtime's envtest (a real kube-apiserver binary); this image has
no cluster, so the controller (deploy/controller.py) runs against THIS
server over actual HTTP — the wire contract is the genuine one:

- typed resource paths (``/apis/{group}/{version}/namespaces/{ns}/{plural}``
  for CRs, ``/api/v1/namespaces/{ns}/pods`` for pods);
- ``metadata.resourceVersion`` from a single monotonically-increasing
  counter, bumped on every write; ``metadata.generation`` bumped only on
  spec changes (ref semantics: status writes don't change generation);
- optimistic concurrency: PUT with a stale resourceVersion → 409 Conflict;
- the **status subresource** (``…/{name}/status``): PATCH/PUT there applies
  ONLY ``.status`` (a spec smuggled into a status patch is discarded), and
  main-resource patches cannot touch ``.status``;
- **watches**: ``GET …?watch=1&resourceVersion=N`` streams newline-delimited
  JSON events (ADDED/MODIFIED/DELETED) for changes after N; a
  resourceVersion older than the retained history returns a 410 Gone
  ERROR event, forcing the client to relist (the informer contract);
- label selectors on list (``labelSelector=k=v,k2=v2``);
- pods get a fake kubelet: created pods transition Pending → Running
  after ``pod_start_delay`` seconds (0 = immediately), so controllers can
  count readiness.

Ref: deploy/cloud/operator/internal/controller/ reconciles against exactly
these verbs; dynamographdeployment_types.go:30 defines the CR this server
stores schema-lessly (CRD validation is the real server's job).
"""

from __future__ import annotations

import asyncio
import copy
import json
import logging
import time
from typing import Optional

from aiohttp import web

logger = logging.getLogger("dynamo.fake_apiserver")

#: watch events retained for resume; older resourceVersions get 410 Gone
WATCH_HISTORY = 4096


def _match_selector(labels: dict, selector: str) -> bool:
    for clause in selector.split(","):
        if not clause:
            continue
        if "=" not in clause:
            return False
        k, v = clause.split("=", 1)
        if labels.get(k) != v:
            return False
    return True


class _Kind:
    """Storage + watch hub for one (path-prefix, plural)."""

    def __init__(self, server: "FakeKubeApiServer", api_version: str, kind: str):
        self.server = server
        self.api_version = api_version
        self.kind = kind
        self.objs: dict[tuple[str, str], dict] = {}  # (ns, name) -> obj
        self.history: list[tuple[int, str, dict]] = []  # (rv, type, obj)
        self.subs: list[asyncio.Queue] = []
        #: rv of the newest event dropped from history — a watch resuming
        #: below this provably missed events (exact per-kind 410 floor; the
        #: global rv counter makes gap-based detection unsound)
        self.truncated_below = 0

    def _emit(self, ev_type: str, obj: dict):
        rv = int(obj["metadata"]["resourceVersion"])
        self.history.append((rv, ev_type, copy.deepcopy(obj)))
        if len(self.history) > WATCH_HISTORY:
            self.truncate(WATCH_HISTORY)
        for q in self.subs:
            q.put_nowait((ev_type, copy.deepcopy(obj)))

    def truncate(self, keep: int):
        """Drop all but the newest ``keep`` events (tests use this to force
        the 410 relist path)."""
        if len(self.history) > keep:
            cut = len(self.history) - keep
            self.truncated_below = self.history[cut - 1][0]
            del self.history[:cut]


class FakeKubeApiServer:
    """aiohttp app serving the contract above. ``start()`` → base_url."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 pod_start_delay: float = 0.0):
        self._rv = 0
        self.pod_start_delay = pod_start_delay
        self._host, self._port = host, port
        self._kinds: dict[str, _Kind] = {}
        self._runner: Optional[web.AppRunner] = None
        self.base_url = ""
        self._pod_timers: set[asyncio.Task] = set()
        #: test hook: ``(name_substring, n)`` → fail the next n creates of
        #: matching objects with 403 (quota-style rejection)
        self.fail_create: Optional[tuple] = None

    def register(self, group: str, version: str, plural: str, kind: str):
        key = f"apis/{group}/{version}" if group else f"api/{version}"
        self._kinds[f"{key}/{plural}"] = _Kind(
            self, f"{group}/{version}" if group else version, kind)

    def next_rv(self) -> int:
        self._rv += 1
        return self._rv

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> str:
        self.register("", "v1", "pods", "Pod")
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._dispatch)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self._host, self._port)
        await site.start()
        self._port = site._server.sockets[0].getsockname()[1]
        self.base_url = f"http://{self._host}:{self._port}"
        return self.base_url

    async def stop(self):
        for t in self._pod_timers:
            t.cancel()
        for kind in self._kinds.values():
            for q in kind.subs:
                q.put_nowait(None)
        if self._runner:
            await self._runner.cleanup()

    # ------------------------------------------------------------- routing
    async def _dispatch(self, req: web.Request) -> web.StreamResponse:
        parts = [p for p in req.path.split("/") if p]
        # {api|apis/group}/{version}/namespaces/{ns}/{plural}[/{name}[/status]]
        try:
            if parts[0] == "api":
                head, rest = "api/" + parts[1], parts[2:]
            else:
                head, rest = "/".join(parts[:3]), parts[3:]
            if rest[0] != "namespaces":
                return web.json_response({"message": "cluster-scoped paths "
                                          "not supported"}, status=404)
            ns, plural, rest = rest[1], rest[2], rest[3:]
        except IndexError:
            return web.json_response({"message": "bad path"}, status=404)
        kind = self._kinds.get(f"{head}/{plural}")
        if kind is None:
            return web.json_response({"message": f"unknown resource {plural}"},
                                     status=404)
        name = rest[0] if rest else None
        sub = rest[1] if len(rest) > 1 else None
        if sub not in (None, "status"):
            return web.json_response({"message": f"unknown subresource {sub}"},
                                     status=404)

        m = req.method
        if m == "GET" and name is None:
            if req.query.get("watch") in ("1", "true"):
                return await self._watch(req, kind, ns)
            return self._list(req, kind, ns)
        if m == "GET":
            obj = kind.objs.get((ns, name))
            if obj is None:
                return self._not_found(kind, name)
            return web.json_response(obj)
        if m == "POST" and name is None:
            return await self._create(req, kind, ns)
        if m in ("PATCH", "PUT") and name:
            return await self._update(req, kind, ns, name,
                                      status_sub=sub == "status",
                                      replace=m == "PUT")
        if m == "DELETE" and name:
            return self._delete(kind, ns, name)
        return web.json_response({"message": "method not allowed"}, status=405)

    def _not_found(self, kind: _Kind, name: str) -> web.Response:
        return web.json_response(
            {"kind": "Status", "status": "Failure", "code": 404, "reason":
             "NotFound", "message": f"{kind.kind} \"{name}\" not found"},
            status=404)

    # --------------------------------------------------------------- verbs
    def _list(self, req: web.Request, kind: _Kind, ns: str) -> web.Response:
        selector = req.query.get("labelSelector", "")
        items = [o for (ons, _), o in sorted(kind.objs.items())
                 if ons == ns and _match_selector(
                     o["metadata"].get("labels", {}), selector)]
        return web.json_response({
            "kind": kind.kind + "List", "apiVersion": kind.api_version,
            "metadata": {"resourceVersion": str(self._rv)},
            "items": items})

    async def _create(self, req, kind: _Kind, ns: str) -> web.Response:
        obj = await req.json()
        name = obj.get("metadata", {}).get("name")
        if not name:
            return web.json_response({"message": "metadata.name required"},
                                     status=422)
        if self.fail_create and self.fail_create[1] > 0 \
                and self.fail_create[0] in name:
            # test hook: simulate quota/scheduling rejection (see
            # fail_create attr) — exercises the controller's gang rollback
            self.fail_create = (self.fail_create[0], self.fail_create[1] - 1)
            return web.json_response(
                {"kind": "Status", "status": "Failure", "code": 403,
                 "reason": "Forbidden", "message": "quota exceeded (test)"},
                status=403)
        if (ns, name) in kind.objs:
            return web.json_response(
                {"kind": "Status", "status": "Failure", "code": 409,
                 "reason": "AlreadyExists",
                 "message": f"{kind.kind} \"{name}\" already exists"},
                status=409)
        md = obj.setdefault("metadata", {})
        md["namespace"] = ns
        md["resourceVersion"] = str(self.next_rv())
        md["generation"] = 1
        obj.setdefault("apiVersion", kind.api_version)
        obj.setdefault("kind", kind.kind)
        kind.objs[(ns, name)] = obj
        kind._emit("ADDED", obj)
        if kind.kind == "Pod":
            self._start_kubelet(kind, ns, name)
        return web.json_response(obj, status=201)

    def _start_kubelet(self, kind: _Kind, ns: str, name: str):
        """Fake kubelet: Pending → Running after pod_start_delay."""
        async def run():
            if self.pod_start_delay:
                await asyncio.sleep(self.pod_start_delay)
            obj = kind.objs.get((ns, name))
            if obj is None:
                return
            obj.setdefault("status", {})["phase"] = "Running"
            obj["metadata"]["resourceVersion"] = str(self.next_rv())
            kind._emit("MODIFIED", obj)

        pod = kind.objs[(ns, name)]
        pod.setdefault("status", {})["phase"] = "Pending"
        t = asyncio.get_running_loop().create_task(run())
        self._pod_timers.add(t)
        t.add_done_callback(self._pod_timers.discard)

    async def _update(self, req, kind: _Kind, ns: str, name: str, *,
                      status_sub: bool, replace: bool) -> web.Response:
        obj = kind.objs.get((ns, name))
        if obj is None:
            return self._not_found(kind, name)
        body = await req.json()
        if replace:
            sent_rv = body.get("metadata", {}).get("resourceVersion")
            if sent_rv is not None and sent_rv != obj["metadata"]["resourceVersion"]:
                return web.json_response(
                    {"kind": "Status", "status": "Failure", "code": 409,
                     "reason": "Conflict",
                     "message": f"the object has been modified (rv {sent_rv} "
                                f"!= {obj['metadata']['resourceVersion']})"},
                    status=409)
        # real-apiserver contract: no NEW finalizers on a terminating
        # object (finalizer removal is how it gets collected). Status-
        # subresource writes are exempt — a real apiserver IGNORES body
        # metadata there rather than rejecting it.
        if not status_sub and obj["metadata"].get("deletionTimestamp"):
            new_fins = set((body.get("metadata") or {})
                           .get("finalizers") or [])
            if new_fins - set(obj["metadata"].get("finalizers") or []):
                return web.json_response(
                    {"kind": "Status", "status": "Failure", "code": 422,
                     "reason": "Invalid",
                     "message": "no new finalizers can be added if the "
                                "object is being deleted"},
                    status=422)
        spec_before = json.dumps(obj.get("spec"), sort_keys=True)
        if status_sub:
            # the status subresource touches ONLY .status
            if replace:
                obj["status"] = body.get("status")
            else:
                obj["status"] = _merge(obj.get("status"), body.get("status"))
        else:
            if replace:
                preserved_status = obj.get("status")
                md = body.setdefault("metadata", {})
                md["namespace"] = ns
                md["name"] = name
                md["generation"] = obj["metadata"]["generation"]
                # deletionTimestamp is server-owned: a replace can neither
                # set nor clear it (k8s contract — only finalizer removal
                # lets a terminating object go)
                md.pop("deletionTimestamp", None)
                if obj["metadata"].get("deletionTimestamp"):
                    md["deletionTimestamp"] = \
                        obj["metadata"]["deletionTimestamp"]
                body["status"] = preserved_status
                kind.objs[(ns, name)] = obj = body
            else:
                body.pop("status", None)  # main resource can't write status
                _merge_into(obj, body)
        if json.dumps(obj.get("spec"), sort_keys=True) != spec_before:
            obj["metadata"]["generation"] = obj["metadata"].get("generation", 1) + 1
        obj["metadata"]["resourceVersion"] = str(self.next_rv())
        # a terminating object whose LAST finalizer was just removed is
        # collected now (k8s finalizer contract)
        if (obj["metadata"].get("deletionTimestamp")
                and not obj["metadata"].get("finalizers")):
            kind.objs.pop((ns, name), None)
            kind._emit("DELETED", obj)
            return web.json_response(obj)
        kind._emit("MODIFIED", obj)
        return web.json_response(obj)

    def _delete(self, kind: _Kind, ns: str, name: str) -> web.Response:
        obj = kind.objs.get((ns, name))
        if obj is None:
            return self._not_found(kind, name)
        # k8s finalizer semantics: while finalizers remain, DELETE only
        # marks deletionTimestamp (MODIFIED); the object disappears when
        # the last finalizer is removed (see _update)
        if obj["metadata"].get("finalizers"):
            if not obj["metadata"].get("deletionTimestamp"):
                obj["metadata"]["deletionTimestamp"] = (
                    time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
                obj["metadata"]["resourceVersion"] = str(self.next_rv())
                kind._emit("MODIFIED", obj)
            return web.json_response(obj)
        kind.objs.pop((ns, name), None)
        obj["metadata"]["resourceVersion"] = str(self.next_rv())
        kind._emit("DELETED", obj)
        return web.json_response(obj)

    # --------------------------------------------------------------- watch
    async def _watch(self, req: web.Request, kind: _Kind, ns: str
                     ) -> web.StreamResponse:
        try:
            since = int(req.query.get("resourceVersion", "0"))
        except ValueError:
            since = 0
        selector = req.query.get("labelSelector", "")

        resp = web.StreamResponse()
        resp.content_type = "application/json"
        await resp.prepare(req)

        async def send(ev_type, obj):
            if obj["metadata"].get("namespace") != ns:
                return
            if not _match_selector(obj["metadata"].get("labels", {}), selector):
                return
            await resp.write(json.dumps(
                {"type": ev_type, "object": obj}).encode() + b"\n")

        q: asyncio.Queue = asyncio.Queue()
        try:
            # 410 Gone: events below the truncation floor are unrecoverable
            if since and since < kind.truncated_below:
                await resp.write(json.dumps({
                    "type": "ERROR",
                    "object": {"kind": "Status", "code": 410,
                               "reason": "Expired",
                               "message": "too old resource version"},
                }).encode() + b"\n")
                await resp.write_eof()
                return resp

            # subscribe BEFORE replay so nothing lands between them; replay
            # everything after `since` (rv=0 replays full retained history —
            # ADDED events for current objects, the list+watch hand-off).
            # An event emitted between subscribe and the history snapshot
            # sits in BOTH — skip live items at or below the max replayed rv
            # so clients never see duplicates (k8s watch contract).
            kind.subs.append(q)
            replayed = since
            for _rv, ev_type, obj in list(kind.history):
                if _rv > since:
                    await send(ev_type, obj)
                    replayed = max(replayed, _rv)
            while True:
                item = await q.get()
                if item is None:
                    break
                ev_type, obj = item
                if int(obj["metadata"]["resourceVersion"]) <= replayed:
                    continue
                await send(ev_type, obj)
            await resp.write_eof()
        except (ConnectionResetError, asyncio.CancelledError,
                ConnectionError):
            pass
        finally:
            if q in kind.subs:
                kind.subs.remove(q)
        return resp


def _merge(base, patch):
    """JSON merge patch (RFC 7386): null deletes, dicts recurse."""
    if not isinstance(patch, dict) or not isinstance(base, dict):
        return copy.deepcopy(patch)
    out = dict(base)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = _merge(out.get(k), v)
    return out


def _merge_into(obj: dict, patch: dict):
    for k, v in patch.items():
        if k == "metadata":
            # merging clients may echo metadata; never let them rewind
            # server-owned fields
            v = {mk: mv for mk, mv in (v or {}).items()
                 if mk not in ("resourceVersion", "generation", "namespace",
                               "deletionTimestamp")}
            obj["metadata"] = _merge(obj.get("metadata"), v)
        elif v is None:
            obj.pop(k, None)
        else:
            obj[k] = _merge(obj.get(k), v)
