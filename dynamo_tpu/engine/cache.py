"""Paged KV cache: device arrays + host-side block pool with prefix cache.

Device side: two arrays [L, num_slots, KV, hd] (num_slots = num_blocks *
block_size), flat slot addressing; block 0 is the reserved NULL block —
padding slot-maps and block-tables point at it and its contents are garbage
by design (attention masks it out).

Host side: ``BlockPool`` mirrors the reference's block lifecycle (ref:
lib/llm/src/block_manager/pool/managed.rs — active refcounted registry +
inactive LRU reuse pool keyed by SequenceHash; and the mocker's KvManager +
LRU evictor — lib/llm/src/mocker/{kv_manager,evictor}.rs): blocks are
refcounted while sequences use them; on release, hash-identified full blocks
park in an LRU prefix cache for reuse; eviction emits the KV-removed events
the router's radix index relies on (ref: kv_router/indexer.rs).
"""

from __future__ import annotations

import logging
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from dynamo_tpu.tokens import SequenceHash

logger = logging.getLogger("dynamo.engine.cache")

NULL_BLOCK = 0


# ---------------------------------------------------------------- int8 cache
#
# A quantized paged cache is a pytree {"q": int8 [L, slots, KV, hd],
# "s": f32 [L, slots, KV]} — symmetric per-(slot, kv-head) scales. On 16 GB
# v5e chips KV capacity is the wall right after weights (r3 verdict weak #3);
# int8 pages ~halve both the footprint and the decode kernel's HBM page
# traffic (the KV-capacity role of the reference's G1 tier,
# lib/llm/src/block_manager/). Scale overhead: 4/hd ≈ 3% at hd=128.
#
# Numerics contract: dequant is exact in f32 (int8 × f32 scale), and
# re-quantizing a dequantized block reproduces the identical (q, s) pair —
# the max |element| of a dequantized block is 127·s, so s survives the
# roundtrip bit-for-bit. KVBM offload/onboard and disagg transfer ride
# f32 bundles and therefore stay deterministic across tiers.

def is_quant_cache(cache) -> bool:
    return isinstance(cache, dict) and "q" in cache and "s" in cache


def cache_shape(cache) -> tuple:
    """[L, slots, KV, hd] shape for plain or quantized caches."""
    return cache["q"].shape if is_quant_cache(cache) else cache.shape


def quantize_kv(x):
    """[..., KV, hd] values → (int8 [..., KV, hd], f32 scales [..., KV]).

    Symmetric, per-(token, head): s = amax/127 over hd, TRUNCATED to bf16
    precision (stored f32). The truncation is what makes the roundtrip
    exact: with an 8-bit-mantissa s, 127·s is exactly representable, so a
    re-quantize computes amax' = 127·s and recovers the identical s — a
    full-mantissa scale loses the contract to one ulp of rounding in
    fl(fl(127·s)/127). Cost: ≤0.2% scale error, noise under int8's 0.4%
    step. jnp in / jnp out, np in / np out (the host requant path must
    match the traced one bit-for-bit)."""
    import jax.numpy as jnp
    import ml_dtypes

    is_np = isinstance(x, np.ndarray)
    xp = np if is_np else jnp
    bf16 = ml_dtypes.bfloat16 if is_np else jnp.bfloat16
    xf = x.astype(xp.float32)
    amax = xp.max(xp.abs(xf), axis=-1)
    s = (xp.maximum(amax, 1e-8) / 127.0).astype(bf16).astype(xp.float32)
    q = xp.clip(xp.round(xf / s[..., None]), -127, 127).astype(xp.int8)
    return q, s


def gather_pages(cache, lidx, slot_idx):
    """Gather [B, T, KV, hd] pages at layer ``lidx`` from a plain OR int8
    cache (used by every XLA-level attention read path: paged, flash
    prefill, ring). Quantized pages dequantize in the gather's consumer —
    XLA fuses the int8 read + scale multiply, so HBM sees 1 byte/element
    either way."""
    if is_quant_cache(cache):
        return dequantize_kv(cache["q"][lidx, slot_idx],
                             cache["s"][lidx, slot_idx])
    return cache[lidx, slot_idx]


def dequantize_kv(q, s, dtype=None):
    """Exact inverse in f32; optional final cast."""
    import jax.numpy as jnp

    xp = jnp if not isinstance(q, np.ndarray) else np
    out = q.astype(xp.float32) * s[..., None]
    return out if dtype is None else out.astype(dtype)


def pack_kv_blocks(q, s):
    """(int8 [..., bs, KV, hd], f32 [..., bs, KV]) → uint8 [..., X] with
    X = bs·KV·(hd+4): q bytes then scale bytes, per leading index.

    The NATIVE bundle format for quantized caches: offload tiers and the
    disagg wire carry ~1.03 bytes/element instead of the 4 an f32 bundle
    costs (and the device→host copy shrinks the same way). Byte order is
    the host's native layout — every TPU-VM in a fleet is little-endian,
    and bundles never persist across architectures."""
    import jax
    import jax.numpy as jnp

    bs, KV, hd = q.shape[-3:]
    lead = q.shape[:-3]
    qb = jax.lax.bitcast_convert_type(q, jnp.uint8).reshape(
        *lead, bs * KV * hd)
    sb = jax.lax.bitcast_convert_type(s, jnp.uint8).reshape(
        *lead, bs * KV * 4)
    return jnp.concatenate([qb, sb], axis=-1)


def unpack_kv_blocks(buf, block_size: int, KV: int, hd: int):
    """Inverse of :func:`pack_kv_blocks`: uint8 [..., X] →
    (int8 [..., bs, KV, hd], f32 [..., bs, KV])."""
    import jax
    import jax.numpy as jnp

    bs = block_size
    lead = buf.shape[:-1]
    nq = bs * KV * hd
    buf = jnp.asarray(buf)
    q = jax.lax.bitcast_convert_type(
        buf[..., :nq], jnp.int8).reshape(*lead, bs, KV, hd)
    s = jax.lax.bitcast_convert_type(
        buf[..., nq:].reshape(*lead, bs, KV, 4), jnp.float32)
    return q, s


def packed_block_width(block_size: int, KV: int, hd: int) -> int:
    """Trailing byte width of a packed quant-bundle row."""
    return block_size * KV * (hd + 4)


class SwapStore:
    """Byte-budgeted accounting for sequences' KV swapped out to host DRAM.

    Preempt-to-swap stages a victim's device pages in host memory (as the
    same value/packed quant bundles the KVBM G2 tier and the disagg wire
    carry) instead of throwing the KV away and re-prefilling. This class
    owns ONLY the budget arithmetic — buffers live on the engine's per-
    sequence swap entries; the scheduler asks reserve() before a swap-out
    and falls back to recompute preemption when the answer is no.

    ``external_used`` shares the budget with the KVBM host tier: when the
    engine runs G2 offload and swap against one DRAM allowance, available
    swap bytes = budget − swap-reserved − G2-resident (and the G2 tier's
    puts symmetrically evict down to budget − swap-reserved — HostTier's
    own ``external_used`` hook, wired by the engine). Thread-safe: the
    reserve happens on the event loop, the release can come from the
    offload worker threads' completion callbacks.
    """

    def __init__(self, budget_bytes: int,
                 external_used: Optional[Callable[[], int]] = None,
                 make_room: Optional[Callable[[int], None]] = None):
        self.budget = max(0, int(budget_bytes))
        self.external_used = external_used
        #: fn(target_bytes): ask the external consumer to shrink to
        #: ``target_bytes`` — without it, a G2 prefix cache that has
        #: naturally filled the shared allowance (LRU caches always do)
        #: would turn every reserve() into a permanent miss and silently
        #: disable swap in exactly the flagship KVBM deployment. G2's
        #: redundant cache copies yield to live-sequence KV.
        self.make_room = make_room
        self.used = 0  # bytes reserved by live swap entries
        self._lock = threading.Lock()

    def _external(self) -> int:
        # a lock-free attribute read on the G2 tier (never a lock
        # acquisition): safe under our lock, and the residual race with a
        # concurrent G2 put is bounded by one block because the tier
        # enforces the shared budget from its side too
        if self.external_used is None:
            return 0
        try:
            return int(self.external_used())
        except Exception:  # a broken G2 probe must not wedge swap
            logger.exception("swap external_used probe failed")
            return 0

    def reserve(self, nbytes: int) -> bool:
        with self._lock:
            ext = self._external()
            avail = self.budget - self.used - ext
            if avail < nbytes and self.make_room is not None and ext > 0:
                # evict the external LRU down far enough that this
                # reservation fits (kvbm takes its own lock; it never
                # takes ours, so the ordering is acyclic)
                try:
                    self.make_room(max(0, ext - (nbytes - avail)))
                except Exception:
                    logger.exception("swap make_room failed")
                avail = self.budget - self.used - self._external()
            if avail < nbytes:
                return False
            self.used += nbytes
            return True

    def release(self, nbytes: int) -> None:
        with self._lock:
            self.used = max(0, self.used - nbytes)


@dataclass
class BlockMeta:
    block_id: int
    ref_count: int = 0
    #: chained sequence hash once the block is full + registered (None = partial)
    seq_hash: Optional[SequenceHash] = None
    #: local tokens-only hash (the router's radix edge key)
    tokens_hash: Optional[int] = None
    parent_hash: Optional[SequenceHash] = None


class BlockPool:
    """Refcounted block allocator with an inactive LRU prefix cache.

    Events: ``on_removed(seq_hashes)`` fires when cached blocks are evicted
    (reused for new data), matching the reference's KV-removed events.
    """

    def __init__(self, num_blocks: int, enable_prefix_caching: bool = True,
                 on_removed: Optional[Callable[[list[int]], None]] = None,
                 ledger=None):
        self.num_blocks = num_blocks
        self.enable_prefix_caching = enable_prefix_caching
        self.on_removed = on_removed
        #: optional WorkerKvLedger (observability/kvaudit.py): the audit
        #: plane's device-tier (g1) residency digest, folded inline at
        #: register/evict/clear — membership mirrors _by_hash exactly
        self.ledger = ledger
        #: fn() called whenever release() returns capacity to the pool —
        #: the engine loop parks on it instead of polling when it is
        #: memory-starved (a freed block is exactly what unblocks plan())
        self.on_freed: Optional[Callable[[], None]] = None
        # block 0 reserved as NULL
        self._free: list[int] = list(range(num_blocks - 1, 0, -1))
        self._meta: dict[int, BlockMeta] = {}
        #: seq_hash -> block_id for *all* registered full blocks (active+inactive)
        self._by_hash: dict[SequenceHash, int] = {}
        #: inactive (refcount 0) cached blocks, LRU order (oldest first)
        self._lru: "OrderedDict[SequenceHash, int]" = OrderedDict()
        #: blocks' worth of KV currently parked on HOST by preempt-to-swap —
        #: accounting DISTINCT from the LRU prefix cache above: these blocks
        #: are NOT device-resident (their device ids were released) but their
        #: sequences are live and will re-allocate on swap-in
        self.swapped_blocks = 0

    # -- swap accounting ---------------------------------------------------

    def note_swapped_out(self, n: int) -> None:
        self.swapped_blocks += n

    def note_swapped_in(self, n: int) -> None:
        self.swapped_blocks = max(0, self.swapped_blocks - n)

    # -- capacity ----------------------------------------------------------

    @property
    def num_free_blocks(self) -> int:
        """Blocks allocatable right now (free list + evictable LRU)."""
        return len(self._free) + len(self._lru)

    @property
    def num_active_blocks(self) -> int:
        return len(self._meta) - len(self._lru)

    def usage(self) -> float:
        usable = self.num_blocks - 1
        return (usable - self.num_free_blocks) / max(1, usable)

    # -- allocation --------------------------------------------------------

    def allocate(self, n: int) -> Optional[list[int]]:
        """Allocate n blocks, evicting LRU-cached blocks if needed.

        Returns None (allocating nothing) if capacity is insufficient.
        """
        if self.num_free_blocks < n:
            return None
        out = []
        evicted: list[int] = []
        for _ in range(n):
            if self._free:
                bid = self._free.pop()
            else:
                h, bid = self._lru.popitem(last=False)
                meta = self._meta.pop(bid)
                self._by_hash.pop(h, None)
                if self.ledger is not None:
                    self.ledger.remove("g1", h)
                evicted.append(meta.seq_hash)
            self._meta[bid] = BlockMeta(block_id=bid, ref_count=1)
            out.append(bid)
        if evicted and self.on_removed:
            self.on_removed(evicted)
        return out

    # -- prefix cache ------------------------------------------------------

    def match_prefix(self, seq_hashes: list[SequenceHash]) -> list[int]:
        """Longest cached prefix: block ids for leading seq hashes, increffed."""
        if not self.enable_prefix_caching:
            return []
        out = []
        for h in seq_hashes:
            bid = self._by_hash.get(h)
            if bid is None:
                break
            meta = self._meta[bid]
            if meta.ref_count == 0:
                self._lru.pop(h, None)
            meta.ref_count += 1
            out.append(bid)
        return out

    def lookup(self, seq_hash: SequenceHash) -> Optional[int]:
        """Block id currently holding ``seq_hash``'s KV (active or LRU-
        cached), or None. Read-only — no incref, no LRU touch; callers
        that gather asynchronously must pin via acquire()/release()."""
        if not self.enable_prefix_caching:
            return None
        return self._by_hash.get(seq_hash)

    def register(self, block_id: int, seq_hash: SequenceHash, tokens_hash: int,
                 parent_hash: Optional[SequenceHash]) -> bool:
        """Mark a full block as identified by its hashes (→ reusable).

        Returns False if an identical block is already registered (duplicate
        content on this worker — caller may dedup, we keep both refs valid).
        """
        meta = self._meta[block_id]
        meta.seq_hash, meta.tokens_hash, meta.parent_hash = seq_hash, tokens_hash, parent_hash
        if not self.enable_prefix_caching:
            return True
        if seq_hash in self._by_hash and self._by_hash[seq_hash] != block_id:
            return False
        if self.ledger is not None and seq_hash not in self._by_hash:
            self.ledger.add("g1", seq_hash)
        self._by_hash[seq_hash] = block_id
        return True

    def acquire(self, block_ids: list[int]) -> None:
        """Incref blocks (e.g. pin for an async offload gather); pairs with
        release(). Cached refcount-0 blocks are pulled out of the LRU."""
        for bid in block_ids:
            meta = self._meta.get(bid)
            if meta is None:
                continue
            if (meta.ref_count == 0 and meta.seq_hash is not None):
                self._lru.pop(meta.seq_hash, None)
            meta.ref_count += 1

    def release(self, block_ids: list[int]) -> None:
        """Decref; refcount-0 blocks go to the LRU cache (if hashed) or free.

        No removed-event fires here: unhashed/duplicate blocks were never
        announced as stored, and a hash's home block parks in the LRU (its
        event fires on eviction in allocate()).
        """
        freed = False
        for bid in block_ids:
            if bid == NULL_BLOCK:
                continue
            meta = self._meta.get(bid)
            if meta is None:
                continue
            meta.ref_count -= 1
            if meta.ref_count > 0:
                continue
            freed = True  # LRU-parked blocks count as allocatable too
            if (meta.seq_hash is not None and self.enable_prefix_caching
                    and self._by_hash.get(meta.seq_hash) == bid):
                self._lru[meta.seq_hash] = bid
                self._lru.move_to_end(meta.seq_hash)
            else:
                self._meta.pop(bid)
                self._free.append(bid)
        if freed and self.on_freed:
            self.on_freed()

    def clear(self) -> None:
        """Drop the entire prefix cache (admin clear_kv_blocks analog)."""
        for h, bid in list(self._lru.items()):
            self._meta.pop(bid, None)
            self._by_hash.pop(h, None)
            if self.ledger is not None:
                self.ledger.remove("g1", h)
            self._free.append(bid)
        self._lru.clear()
        if self.on_removed:
            self.on_removed(None)  # None = cleared-all sentinel
        if self.on_freed:
            self.on_freed()


def allocate_device_cache(cfg, num_blocks: int, block_size: int, mesh=None,
                          dtype=None, global_arrays: bool = False):
    """Allocate the [L, num_slots, KV, hd] k/v cache arrays (zeros).

    ``dtype="int8"`` returns quantized caches ({"q": int8, "s": f32 scales}
    pytrees — see module int8 notes); any other dtype (or None = model
    dtype) returns plain arrays.

    ``global_arrays`` (multi-host meshes): zeros are materialized through a
    jitted creation so shards land on non-addressable devices too —
    device_put can only reach this process's devices.
    """
    import jax.numpy as jnp
    import jax

    from dynamo_tpu.engine.model import cache_shardings

    quant = dtype == "int8" or (dtype is not None
                                and jnp.dtype(dtype) == jnp.int8)
    dtype = jnp.dtype(cfg.dtype) if (dtype is None or quant) else dtype
    (kh, kd), (vh, vd) = cfg.kv_cache_spec
    k_shape = (cfg.num_layers, num_blocks * block_size, kh, kd)
    v_shape = (cfg.num_layers, num_blocks * block_size, vh, vd)

    def alloc(shape, dt, sh):
        if mesh is not None and global_arrays:
            from dynamo_tpu.parallel.multihost import global_zeros

            return global_zeros(shape, dt, sh)
        z = jnp.zeros(shape, dt)
        return jax.device_put(z, sh) if sh is not None else z

    sh = cache_shardings(mesh, cfg, quant=quant) if mesh is not None else None

    def one(shape):
        if not quant:
            return alloc(shape, dtype, sh)
        return {"q": alloc(shape, jnp.int8, sh["q"] if sh else None),
                "s": alloc(shape[:-1], jnp.float32, sh["s"] if sh else None)}

    return one(k_shape), one(v_shape)


#: HBM per chip by device-kind substring — the sizing fallback when
#: memory_stats() is unavailable (observed on tunneled/axon devices: the
#: r4 TPU bench ran the whole fleet on the 512-block default, 8k tokens of
#: KV for 35k tokens of demand → preemption thrash at 31 tok/s)
DEVICE_HBM_BYTES = (
    ("v5 lite", 16 << 30), ("v5e", 16 << 30),
    ("v5p", 95 << 30), ("v4", 32 << 30), ("v6", 32 << 30),
)


def bounded_memory_stats(dev, timeout: float = 5.0) -> dict:
    """``dev.memory_stats()`` with a hard timeout. Over a tunneled (axon)
    device the bare call does not throw — it HANGS (observed r4: never
    returned in 400 s). A plain daemon thread carries the probe: unlike a
    ThreadPoolExecutor worker (non-daemon since py3.9), a wedged daemon
    can't stall interpreter exit. Raises TimeoutError on expiry."""
    import threading

    box: list = []

    def probe():
        try:
            box.append(dev.memory_stats())
        except Exception as e:  # surfaced to the caller below
            box.append(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout)
    if not box:
        raise TimeoutError(f"memory_stats did not answer in {timeout}s")
    if isinstance(box[0], Exception):
        raise box[0]
    return box[0]


def tree_nbytes(params) -> int:
    """Resident bytes of a params pytree (int4 packs two weights/byte on
    TPU HBM — itemsize reports 1)."""
    import jax

    total = 0
    for x in jax.tree_util.tree_leaves(params):
        n = x.size // 2 if x.dtype.name == "int4" else x.size * x.dtype.itemsize
        total += n
    return total


def hbm_sized_num_blocks(cfg, block_size: int, fraction: float,
                         tp_size: int = 1, default: int = 512,
                         kv_cache_dtype: Optional[str] = None,
                         params_bytes: int = 0) -> int:
    """Size the block count from free device memory (TPU) or a default (CPU).

    ``kv_cache_dtype="int8"``: 1 byte/element + 4-byte f32 scale per
    (slot, head) — block capacity roughly doubles vs bf16.

    ``params_bytes``: resident weight bytes, used by the estimate path when
    ``memory_stats()`` is unsupported (tunneled devices): free ≈ chip HBM −
    params − 1 GiB runtime headroom."""
    import jax

    free = None
    try:
        dev = jax.devices()[0]
        stats = bounded_memory_stats(dev)
        free = stats["bytes_limit"] - stats["bytes_in_use"]
    except Exception:
        try:
            dev = jax.devices()[0]
            kind = getattr(dev, "device_kind", "").lower()
            if dev.platform == "tpu":
                total = next((b for sub, b in DEVICE_HBM_BYTES
                              if sub in kind), 16 << 30)
                free = max(0, total - params_bytes - (1 << 30))
        except Exception:
            pass
    if free is None:
        return default
    (kh, kd), (vh, vd) = cfg.kv_cache_spec
    # MLA's single-latent-head cache is not TP-shardable (replicated)
    k_heads = kh // max(1, tp_size) if kh % max(1, tp_size) == 0 else kh
    v_heads = vh // max(1, tp_size) if vh % max(1, tp_size) == 0 else vh
    if kv_cache_dtype == "int8":
        per_slot = k_heads * (kd + 4) + v_heads * (vd + 4)
    else:
        per_slot = (k_heads * kd + v_heads * vd) * (
            2 if cfg.dtype == "bfloat16" else 4)
    bytes_per_block = cfg.num_layers * block_size * per_slot
    n = int(free * fraction / max(1, bytes_per_block))
    return max(16, n)
