"""Direct worker→requester TCP response streaming.

The token hot path must not transit the control-plane hub, so responses stream
over a per-process TCP server exactly like the reference's response plane
(ref: lib/runtime/src/pipeline/network/tcp/server.rs:62): the requester
registers a pending stream and hands ``ConnectionInfo`` to the worker inside
the request envelope; the worker connects back, sends a prologue identifying
the stream, then pumps framed data until a ``complete`` or ``err`` sentinel.

The same TCP connection is used *bidirectionally*: the requester can push a
``cancel`` frame upstream, which trips the worker-side request context — this
is how client disconnects abort generation on the engine.

In-process callers short-circuit through an asyncio queue (no sockets), which
is also what single-process deployments and most tests use.
"""

from __future__ import annotations

import asyncio
import logging
import socket
import uuid
from dataclasses import dataclass
from typing import Any, AsyncIterator, Optional

from dynamo_tpu.runtime.chaos import ChaosError, get_chaos
from dynamo_tpu.runtime.codec import pack_frame, read_frame, write_frame
from dynamo_tpu.runtime.context import (
    STREAM_ERR_MSG,
    Context,
    StreamError,
    stream_error_from_wire,
)

logger = logging.getLogger("dynamo.response_plane")

_COMPLETE = {"t": "complete"}

#: per-stream buffer cap: beyond this the server stops reading the worker's
#: socket, letting TCP flow control throttle the producer (backpressure)
STREAM_QUEUE_MAX = 1024


def _put_sentinel(q: asyncio.Queue, frame: dict) -> None:
    """Deliver a terminal frame even when the queue is full (drop oldest data)."""
    while True:
        try:
            q.put_nowait(frame)
            return
        except asyncio.QueueFull:
            try:
                q.get_nowait()
            except asyncio.QueueEmpty:
                pass


@dataclass(frozen=True)
class ConnectionInfo:
    host: str
    port: int
    stream_id: str
    #: set for in-process short-circuit streams
    local: bool = False

    def to_wire(self) -> dict:
        return {"host": self.host, "port": self.port, "stream_id": self.stream_id, "local": self.local}

    @staticmethod
    def from_wire(d: dict) -> "ConnectionInfo":
        return ConnectionInfo(d["host"], d["port"], d["stream_id"], d.get("local", False))


class ResponseReceiver:
    """Requester-side view of one response stream.

    The queue carries *frames* ({"t": "data"/"complete"/"err"}), never raw
    payloads, so user data can never collide with the stream sentinels.
    """

    def __init__(self, queue: "asyncio.Queue[Any]", on_cancel=None):
        self._queue = queue
        self._on_cancel = on_cancel
        #: fired once when the stream terminates (complete/err) or the
        #: consumer abandons it — lets a Client deregister this stream
        #: from its per-instance liveness tracking (proactive death
        #: handling, docs/robustness.md)
        self.on_done = None
        #: frames CONSUMED so far; with the queue depth this gives a
        #: monotonic arrived-frame counter (activity()) — the liveness
        #: signal the worker-lost grace window compares across time
        self._consumed = 0

    def activity(self) -> int:
        """Monotonic count of frames that have ARRIVED on this stream
        (consumed + still queued) — unchanged across a grace window means
        the producer is dead, not draining."""
        return self._consumed + self._queue.qsize()

    def __aiter__(self) -> AsyncIterator[Any]:
        return self._iter()

    def _done(self):
        cb, self.on_done = self.on_done, None
        if cb is not None:
            try:
                cb()
            except Exception:
                logger.exception("stream on_done callback failed")

    def fail(self, msg: str, retryable: bool = True,
             code: Optional[str] = None) -> None:
        """Terminate the stream from the REQUESTER side with a typed error
        frame (e.g. the producing instance's lease expired — the worker
        will never send a terminal frame itself). Sentinel delivery drops
        buffered data if the queue is full; exact token accounting is the
        Migration layer's job via its accumulated-token replay."""
        frame = {"t": "err", "msg": msg, "retryable": retryable}
        if code is not None:
            frame["code"] = code
        _put_sentinel(self._queue, frame)

    async def _iter(self):
        try:
            while True:
                frame = await self._queue.get()
                self._consumed += 1
                t = frame.get("t")
                if t == "data":
                    yield frame.get("d")
                elif t == "complete":
                    return
                elif t == "err":
                    # typed rehydration: the error class (and so Migration's
                    # retry decision) survives the wire hop
                    raise stream_error_from_wire(
                        frame.get("msg", STREAM_ERR_MSG), frame.get("code"),
                        frame.get("retryable", True))
        finally:
            self._done()

    async def cancel(self):
        """Tell the producing worker to stop."""
        if self._on_cancel:
            await self._on_cancel()


class ResponseStreamServer:
    """Per-process TCP server accepting worker response connections."""

    def __init__(self, host: Optional[str] = None):
        self._host = host or _default_host()
        self._server: Optional[asyncio.base_events.Server] = None
        self._port = 0
        self._pending: dict[str, tuple[asyncio.Queue, Context]] = {}

    async def start(self):
        if self._server is not None:
            return
        self._server = await asyncio.start_server(self._on_conn, "0.0.0.0", 0)
        self._port = self._server.sockets[0].getsockname()[1]
        logger.debug("response plane listening on %s:%d", self._host, self._port)

    async def stop(self):
        if self._server:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        for q, _ in self._pending.values():
            _put_sentinel(q, {"t": "err", "msg": STREAM_ERR_MSG})
        self._pending.clear()

    def register_stream(self, ctx: Context) -> tuple[ConnectionInfo, ResponseReceiver]:
        """Register a pending stream; returns (info for the worker, receiver)."""
        assert self._server is not None, "ResponseStreamServer not started"
        stream_id = uuid.uuid4().hex
        q: asyncio.Queue = asyncio.Queue(maxsize=STREAM_QUEUE_MAX)
        self._pending[stream_id] = (q, ctx)
        info = ConnectionInfo(self._host, self._port, stream_id)

        async def on_cancel():
            ctx.cancel()

        return info, ResponseReceiver(q, on_cancel)

    def abandon_stream(self, info: ConnectionInfo):
        self._pending.pop(info.stream_id, None)

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            prologue = await read_frame(reader)
            stream_id = prologue.get("stream_id")
            entry = self._pending.pop(stream_id, None)
            if entry is None:
                await write_frame(writer, {"t": "err", "msg": f"unknown stream {stream_id}"})
                writer.close()
                return
            q, ctx = entry
            await write_frame(writer, {"t": "ok"})

            async def cancel_pump():
                # Push a cancel frame upstream when our local context cancels.
                try:
                    await ctx.wait_cancelled()
                    await write_frame(writer, {"t": "cancel"})
                except Exception:
                    pass

            cancel_task = asyncio.get_running_loop().create_task(cancel_pump())
            try:
                while True:
                    frame = await read_frame(reader)
                    t = frame.get("t")
                    if t == "data":
                        await q.put(frame)  # blocks when full -> TCP backpressure
                    elif t in ("complete", "err"):
                        _put_sentinel(q, frame)
                        return
            except (asyncio.IncompleteReadError, ConnectionError):
                _put_sentinel(q, {"t": "err", "msg": STREAM_ERR_MSG})
            finally:
                cancel_task.cancel()
        except Exception:
            logger.exception("response connection failed")
        finally:
            try:
                writer.close()
            except Exception:
                pass


class StreamSender:
    """Worker-side handle for pushing response frames back to the requester.

    Sends are CORKED: frames are written to the transport without awaiting
    ``drain()`` (the event loop flushes writes to the socket on its own —
    drain is only backpressure), and the drain round trip is paid once per
    ``SEND_HIGH_WATER`` bytes or on flush/complete instead of once per
    token frame. ``send_many()`` packs a whole batch into one write.
    """

    #: unflushed bytes after which send()/send_many() await one drain —
    #: bounds worker-side memory when the requester reads slowly (TCP flow
    #: control then throttles us through the paused transport)
    SEND_HIGH_WATER = 64 * 1024

    def __init__(self):
        self._queue: Optional[asyncio.Queue] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reader_task: Optional[asyncio.Task] = None
        self._closed = False
        self._unflushed = 0

    @staticmethod
    async def connect(info: ConnectionInfo, ctx: Optional[Context] = None) -> "StreamSender":
        s = StreamSender()
        reader, writer = await asyncio.open_connection(info.host, info.port)
        await write_frame(writer, {"stream_id": info.stream_id})
        ack = await read_frame(reader)
        if ack.get("t") != "ok":
            writer.close()
            raise StreamError(ack.get("msg", "handshake rejected"))
        s._writer = writer

        async def cancel_listener():
            # Watch for upstream cancel frames and trip the worker context.
            try:
                while True:
                    frame = await read_frame(reader)
                    if frame.get("t") == "cancel" and ctx is not None:
                        ctx.cancel()
            except (asyncio.IncompleteReadError, ConnectionError):
                # Requester went away: cancel generation.
                if ctx is not None and not s._closed:
                    ctx.cancel()

        s._reader_task = asyncio.get_running_loop().create_task(cancel_listener())
        return s

    @staticmethod
    def local(queue: asyncio.Queue) -> "StreamSender":
        s = StreamSender()
        s._queue = queue
        return s

    @staticmethod
    async def _chaos_gate() -> None:
        """``stream.send`` chaos hook, shared by both transports. Runs
        BEFORE anything is enqueued/written so a "dropped" batch is never
        partially delivered — token accounting across a migration stays
        exact. drop and error both kill the send (transport loss)."""
        chaos = get_chaos()
        if chaos is None:
            return
        await chaos.pre("stream.send")
        if chaos.should_drop("stream.send"):
            raise ChaosError("injected drop at stream.send")

    async def send(self, data: Any) -> None:
        await self._chaos_gate()
        if self._queue is not None:
            await self._queue.put({"t": "data", "d": data})
        else:
            self._write_corked(pack_frame({"t": "data", "d": data}))
            await self._maybe_drain()

    async def send_many(self, items: list) -> None:
        """Send a batch of data frames as ONE transport write (and at most
        one drain) — the coalesced path for per-step token batches."""
        if not items:
            return
        await self._chaos_gate()
        if self._queue is not None:
            for d in items:
                await self._queue.put({"t": "data", "d": d})
        else:
            self._write_corked(b"".join(
                pack_frame({"t": "data", "d": d}) for d in items))
            await self._maybe_drain()

    def _write_corked(self, buf: bytes) -> None:
        self._writer.write(buf)
        self._unflushed += len(buf)

    async def _maybe_drain(self) -> None:
        if self._unflushed >= self.SEND_HIGH_WATER:
            await self.flush()

    async def flush(self) -> None:
        """Pay the backpressure drain now (no-op when nothing is corked)."""
        if self._writer is not None and self._unflushed:
            self._unflushed = 0
            await self._writer.drain()

    async def complete(self) -> None:
        self._closed = True
        if self._queue is not None:
            _put_sentinel(self._queue, _COMPLETE)
        else:
            try:
                self._unflushed = 0
                await write_frame(self._writer, _COMPLETE)
            finally:
                self._teardown()

    async def error(self, msg: str, code: Optional[str] = None,
                    retryable: bool = True) -> None:
        """Terminate the stream with a typed error frame. ``retryable``
        False marks the failure terminal (overload/deadline): the receiver
        raises a TerminalStreamError and Migration will not re-send."""
        self._closed = True
        frame = {"t": "err", "msg": msg, "retryable": retryable}
        if code is not None:
            frame["code"] = code
        if self._queue is not None:
            _put_sentinel(self._queue, frame)
        else:
            try:
                await write_frame(self._writer, frame)
            finally:
                self._teardown()

    def _teardown(self):
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer:
            try:
                self._writer.close()
            except Exception:
                pass


def make_local_stream(ctx: Context) -> tuple[ConnectionInfo, ResponseReceiver, asyncio.Queue]:
    """In-process short-circuit stream (no sockets)."""
    q: asyncio.Queue = asyncio.Queue(maxsize=STREAM_QUEUE_MAX)
    info = ConnectionInfo("", 0, uuid.uuid4().hex, local=True)

    async def on_cancel():
        ctx.cancel()

    return info, ResponseReceiver(q, on_cancel), q


def _default_host() -> str:
    """Best-effort routable address of this host (TPU-VM DCN interface).

    Override with ``DYN_RESPONSE_HOST`` when autodetection picks the wrong
    interface; a loopback fallback is logged loudly since it breaks
    cross-host response streams.
    """
    import os

    override = os.environ.get("DYN_RESPONSE_HOST")
    if override:
        return override
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except Exception:
        pass
    try:
        ip = socket.gethostbyname(socket.gethostname())
        if not ip.startswith("127."):
            return ip
    except Exception:
        pass
    logger.warning(
        "could not detect a routable host address; advertising 127.0.0.1 "
        "(cross-host response streams will fail — set DYN_RESPONSE_HOST)"
    )
    return "127.0.0.1"
